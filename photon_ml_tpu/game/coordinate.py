"""Training coordinates: the per-coordinate update/score contract.

Reference: photon-lib .../algorithm/Coordinate.scala:28-81 (updateModel folds
residual scores into offsets then optimizes; score produces this coordinate's
contribution), photon-api .../algorithm/FixedEffectCoordinate.scala:35-166 and
RandomEffectCoordinate.scala:39-232.

TPU-native shape:
- Data is laid out on device ONCE at coordinate construction (the reference
  re-broadcasts/joins per update).  Updates re-enter the same jitted solver
  with new residual offsets — same shapes, zero recompilation.
- The fixed effect solves over the ``data``-sharded batch (GSPMD all-reduce).
- The random effect solves all entities at once: vmapped solver over padded
  entity buckets (parallel/bucketing.py), replacing per-entity serial
  executor solves (RandomEffectCoordinate.scala:114-127).
- Scoring is total: every sample gets this coordinate's raw score (the
  reference's active+passive union), so residual bookkeeping in the descent
  loop is positionally aligned.
"""

from __future__ import annotations

import time as _time
from typing import Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from photon_ml_tpu.core.batch import DenseBatch, SparseBatch
from photon_ml_tpu.core.losses import loss_for_task
from photon_ml_tpu.core.normalization import NormalizationContext, no_normalization
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.game.config import CoordinateConfig, FixedEffectConfig, RandomEffectConfig
from photon_ml_tpu.game.data import GameData, SparseShard
from photon_ml_tpu.models.game import DatumScoringModel, FixedEffectModel, RandomEffectModel
from photon_ml_tpu.models.glm import Coefficients
from photon_ml_tpu.obs import get_registry, set_family_bounds
from photon_ml_tpu.obs.trace import span as obs_span
from photon_ml_tpu.obs.watch.attribution import attribute as obs_attribute
from photon_ml_tpu.opt.solve import make_solver
from photon_ml_tpu.opt.types import SolverResult
from photon_ml_tpu.parallel.bucketing import bucket_by_entity, stacked_coefficients
from photon_ml_tpu.parallel.mesh import replicate, shard_batch
from photon_ml_tpu.types import (OptimizerType, ProjectorType, TaskType,
                                 VarianceComputationType)

Array = jax.Array

# Per-entity bucket solves live in ms..minutes, not the default 1µs..67s
# span ladder — register sane bins once at import (obs follow-on: per-family
# histogram bound overrides).  100µs .. ~7min, factor 2.
set_family_bounds("solve_bucket_seconds",
                  [1e-4 * (2.0 ** i) for i in range(23)])


def _slots_from(slot_of: Dict[int, int], entity_ids: np.ndarray) -> np.ndarray:
    """Vectorized entity-id -> slot lookup (-1 for unknown ids)."""
    if not slot_of:
        return np.full(len(entity_ids), -1, np.int32)
    keys = np.fromiter(slot_of.keys(), np.int64, len(slot_of))
    vals = np.fromiter(slot_of.values(), np.int32, len(slot_of))
    order = np.argsort(keys)
    keys, vals = keys[order], vals[order]
    pos = np.searchsorted(keys, entity_ids)
    pos = np.clip(pos, 0, len(keys) - 1)
    hit = keys[pos] == entity_ids
    return np.where(hit, vals[pos], -1).astype(np.int32)


class Coordinate:
    """update/score contract (reference Coordinate.scala:28-81)."""

    coordinate_id: str
    _n: int

    @property
    def num_samples(self) -> int:
        return self._n

    def _base_offset_host(self) -> np.ndarray:
        """Dataset base offsets [n] (residual offsets are added on top)."""
        return self._base_offset

    def update(self, total_offsets: np.ndarray, seed: int,
               init: Optional[DatumScoringModel]) -> Tuple[DatumScoringModel, object]:
        """Train with residual-folded offsets; returns (model, tracker)."""
        raise NotImplementedError

    def score(self, model: DatumScoringModel) -> np.ndarray:
        """This coordinate's raw score for every training sample."""
        raise NotImplementedError

    # --- traceable-step interface (fully-jitted sweeps, game/fused.py) ---
    # The host-paced contract above crosses the device boundary per call; the
    # methods below keep the whole descent on device: ``state`` is a pytree of
    # device arrays carried through lax.scan.  Both built-in coordinate
    # flavors implement every configuration (down-sampling, variances,
    # projection all run in-program); a custom Coordinate subclass that only
    # implements the host-paced contract inherits these raising defaults, and
    # the estimator's fused="auto" then falls back to CoordinateDescent.

    @property
    def dtype(self):
        return self._dtype

    def init_sweep_state(self, init: Optional[DatumScoringModel] = None):
        """Host: initial device state (cold or warm-started from a model)."""
        raise NotImplementedError

    def sweep_data(self):
        """Host: pytree of device arrays the traceable steps read (the design
        matrices).  The fused sweep passes it back through ``trace_*``'s
        ``data=`` so the big arrays enter the compiled program as ARGUMENTS —
        closed-over jax.Arrays lower to baked XLA constants and compile time
        grows linearly with constant bytes."""
        return None

    def trace_update(self, state, offsets: Array,
                     reg: "Optional[Regularization]" = None,
                     key=None, data=None) -> Tuple[object, Array]:
        """Traceable: one update against residual-folded ``offsets[n]``;
        returns (state', this coordinate's new score[n]).  ``reg`` (possibly
        traced) overrides the config's regularization weights so one compiled
        sweep serves a whole reg grid.  ``key``: per-(iteration, coordinate)
        PRNG key the fused sweep folds for stochastic per-update work
        (down-sampling); coordinates without such work ignore it.  ``data``:
        this coordinate's ``sweep_data()`` passed back as traced arguments
        (None = read the coordinate's own device arrays, the host-paced
        path)."""
        raise NotImplementedError

    def trace_publish(self, state, data=None) -> Array:
        """Traceable: state -> the publishable coefficient array.  ``data``:
        this coordinate's ``sweep_data()`` (same convention as trace_update)."""
        raise NotImplementedError

    def init_sweep_variances(self):
        """Host: placeholder pytree the sweep carries for this coordinate's
        variances (a zero-length array when variance=NONE)."""
        return jnp.zeros(0)

    def trace_variances(self, state, offsets: Array,
                        reg: "Optional[Regularization]" = None, key=None,
                        data=None):
        """Traceable: variances at this update's iterate/offsets/reg; same
        pytree structure as ``init_sweep_variances()``."""
        raise NotImplementedError

    def export_variances(self, v) -> np.ndarray:
        """Host: program variance output -> array for the published model."""
        raise NotImplementedError

    def export_model(self, published: np.ndarray) -> DatumScoringModel:
        """Host: the array from trace_publish -> this coordinate's model."""
        raise NotImplementedError

    def merge_carry_through(self, model: DatumScoringModel,
                            init: Optional[DatumScoringModel]
                            ) -> DatumScoringModel:
        """Host: fold warm-start state this update could NOT retrain into the
        published model (reference RandomEffectCoordinate.updateModel's
        leftOuterJoin :114-127: a prior per-entity model with no active data
        passes through unchanged).  Default: nothing to carry."""
        return model

    def carry_through_scores(self, init: Optional[DatumScoringModel]
                             ) -> "Optional[np.ndarray]":
        """Host: per-sample scores [n] of the warm-start state that
        merge_carry_through would pass through (the carried entities'
        contribution).  The fused sweep folds this CONSTANT into its base
        offsets so every in-program residual matches the host loop, whose
        re-scoring of the merged model includes it.  None = nothing
        carried."""
        return None

    # --- external (validation) scoring for fused validated sweeps --------
    # The fused validated program (game/fused.FusedSweep.run_validated)
    # scores a HELD-OUT sample set with each coordinate's published
    # coefficients inside the scanned program; these two methods are that
    # contract.  Subclasses without them inherit raising defaults and the
    # estimator falls back to the host-paced CoordinateDescent.

    def external_data(self, data: "GameData"):
        """Host: pytree of device arrays for scoring ``data`` with this
        coordinate's published coefficients inside a traced program
        (the validated sweep passes it back through ``trace_score_external``
        as ARGUMENTS — the same baked-constant-avoidance convention as
        ``sweep_data``)."""
        raise NotImplementedError

    def trace_score_external(self, published: Array, vdata) -> Array:
        """Traceable: published coefficient array + ``external_data``
        pytree -> this coordinate's raw score for every external sample
        (the traced twin of ``model.score(data)`` on the exported model)."""
        raise NotImplementedError

    def carry_through_scores_on(self, init: "Optional[DatumScoringModel]",
                                data: "GameData") -> "Optional[np.ndarray]":
        """Host: per-sample scores on ``data`` of the warm-start state this
        coordinate cannot retrain (``carry_through_scores``' semantics on an
        EXTERNAL sample set) — the validated sweep folds this constant into
        its held-out score base.  None = nothing carried."""
        return None

    def sweep_key(self) -> tuple:
        """Identity of this coordinate's compiled sweep contribution: the
        device data layout + every config field EXCEPT the regularization
        VALUES (those enter the program as traced arguments).  The L1 regime
        (l1 > 0) must survive in the key: make_solver dispatches OWLQN vs
        L-BFGS statically on it, so a reg override may never cross the
        smooth/L1 boundary inside one compiled sweep."""
        import dataclasses

        regime = Regularization(l1=1.0 if self.config.reg.l1 > 0.0 else 0.0)
        return (self.data_key(),
                dataclasses.replace(self.config, reg=regime))


def _storage_np_dtype(storage_dtype: Optional[str]):
    """Resolve a config storage dtype string to a numpy dtype (ml_dtypes
    registers bfloat16 etc. with numpy), or None when unset."""
    if storage_dtype is None:
        return None
    import ml_dtypes  # noqa: F401  (registers the 16-bit dtypes with numpy)

    return np.dtype(storage_dtype)


class FixedEffectCoordinate(Coordinate):
    """Global GLM coordinate (reference FixedEffectCoordinate.scala:35-166)."""

    def __init__(self, coordinate_id: str, data: GameData, config: FixedEffectConfig,
                 task: TaskType, mesh: Optional[Mesh] = None,
                 norm: Optional[NormalizationContext] = None, dtype=np.float32):
        self.coordinate_id = coordinate_id
        self.config = config
        self.task = task
        self.mesh = mesh
        self.dim = data.shard_dim(config.feature_shard)
        self._n = data.num_samples
        self._dtype = dtype
        self._base_offset = np.asarray(data.offset, np.float64)

        shard_data = data.features[config.feature_shard]
        y = jnp.asarray(np.asarray(data.y, dtype))
        # Default offsets (all-zero) / weights (all-one) are created ON
        # DEVICE: an [n]-sized constant needn't cross the wire (at chip
        # scale over a slow transport those two uploads cost more than the
        # labels themselves).
        offs_np = np.asarray(data.offset, dtype)
        offs0 = (jnp.zeros(self._n, dtype) if not offs_np.any()
                 else jnp.asarray(offs_np))
        wt_np = np.asarray(data.weight, dtype)
        wt0 = (jnp.ones(self._n, dtype) if np.all(wt_np == 1.0)
               else jnp.asarray(wt_np))
        # Storage narrowing happens ON HOST so the device transfer and the
        # resident array are storage-width from the start (an on-device cast
        # would transfer f32 and transiently hold both copies in HBM).
        x_dtype = _storage_np_dtype(config.storage_dtype) or dtype
        # The design matrix is the one giant host->device transfer in a fit;
        # chunked_device_put bounds each RPC on fragile transports (axon).
        from photon_ml_tpu.utils.transfer import chunked_device_put
        if isinstance(shard_data, SparseShard):
            batch = SparseBatch(
                indices=chunked_device_put(shard_data.indices),
                values=chunked_device_put(shard_data.values, x_dtype),
                y=y, offset=offs0, weight=wt0, dim=shard_data.dim)
        else:
            batch = DenseBatch(x=chunked_device_put(shard_data, x_dtype),
                               y=y, offset=offs0, weight=wt0)
        # One-time row padding to the fused-kernel block granule so the
        # pallas path never re-pads (and re-copies X) per solver call.
        # Narrow float storage (bf16/f16) keeps the pallas path — the
        # kernels take storage-width MXU operands with f32 accumulation
        # (GLMObjective._fused_eligible), so the single-HBM-pass advantage
        # compounds with the halved bytes.  Wider-than-solver storage (f64)
        # falls back to XLA.
        from photon_ml_tpu.ops.fused_glm import (_pick_block_rows, _pad_rows,
                                                 eligible,
                                                 storage_narrowing_ok)
        from photon_ml_tpu.parallel.mesh import FEATURE_AXIS, padded_dim

        # Feature-axis (model-parallel) sharding: active only when the mesh
        # actually has a feature axis > 1, so the same config is valid on any
        # mesh (mesh-agnostic property, SURVEY §4).
        self._fs = bool(getattr(config, "feature_sharded", False)) \
            and mesh is not None and mesh.shape[FEATURE_AXIS] > 1
        self._d_pad = padded_dim(self.dim, mesh) if self._fs else self.dim
        # same predicate GLMObjective._fused_eligible consults at solve time
        # — the pre-pad must never disagree with the per-call gate
        fused_ok = (storage_narrowing_ok(x_dtype, dtype) and eligible(batch)
                    and not self._fs)  # pallas kernels assume full-width w
        if mesh is not None:
            if fused_ok:
                # pad so each device's LOCAL shard is a block multiple
                from photon_ml_tpu.parallel.mesh import DATA_AXIS

                n_dev = mesh.shape[DATA_AXIS]
                local = -(-batch.num_examples // n_dev)
                bn = _pick_block_rows(
                    local, batch.dim, np.dtype(batch.x.dtype).itemsize)
                batch = _pad_rows(batch, (-(-local // bn) * bn) * n_dev)
            batch = shard_batch(
                batch, mesh,
                feature_axis=FEATURE_AXIS
                if (self._fs and isinstance(batch, DenseBatch)) else None)
        elif fused_ok:
            batch = _pad_rows(batch, _pick_block_rows(
                *batch.x.shape, np.dtype(batch.x.dtype).itemsize))
        self._batch = batch
        self._padded_n = batch.num_examples
        self._base_weight = batch.weight

        norm = norm or no_normalization()
        # match the batch dtype or the normalization algebra promotes the
        # whole solver carry (f64 stats ctx x f32 batch -> while_loop error)
        self._norm = norm.replace(
            factors=None if norm.factors is None else jnp.asarray(norm.factors, dtype),
            shifts=None if norm.shifts is None else jnp.asarray(norm.shifts, dtype))
        if self._fs and self._d_pad != self.dim:
            # padded coefficient slots: identity scale, no shift — they see
            # only zero feature columns so they stay pinned at 0
            pad = self._d_pad - self.dim
            self._norm = self._norm.replace(
                factors=None if self._norm.factors is None
                else jnp.pad(self._norm.factors, (0, pad), constant_values=1.0),
                shifts=None if self._norm.shifts is None
                else jnp.pad(self._norm.shifts, (0, pad)))
        self._bind_solver()
        # The batch is an ARGUMENT of every jitted program, never a closure:
        # closed-over jax.Arrays lower to baked XLA constants, and compile
        # time grows linearly with constant bytes (~9s per GB-touch on CPU;
        # far worse on the TPU backend) — X here is the biggest array in the
        # system.
        self._score = jax.jit(lambda w, batch: batch.margins(w))

    def _bind_solver(self) -> None:
        # Both paths use the pallas fused kernels (ops/fused_glm.py) where
        # eligible: X streams through VMEM once per value_and_grad instead of
        # 2-3 XLA passes.  Under a mesh the objective runs as explicit SPMD
        # (shard_map + one psum per evaluation, parallel/fixed.py) — GSPMD
        # cannot auto-partition a pallas custom call, shard_map runs it
        # per-device on local rows.
        objective = GLMObjective(loss=loss_for_task(self.task), reg=self.config.reg,
                                 norm=self._norm, fused=not self._fs)
        if self._fs and isinstance(self._batch, SparseBatch):
            from photon_ml_tpu.parallel.fixed import ShardSparseObjective
            from photon_ml_tpu.parallel.mesh import FEATURE_AXIS

            objective = ShardSparseObjective(
                objective, self.mesh,
                self._d_pad // self.mesh.shape[FEATURE_AXIS])
        elif self._fs:
            # dense + feature-sharded: plain objective; GSPMD partitions the
            # margin/gradient contractions from the (data, feature) shardings
            pass
        elif self.mesh is not None:
            from photon_ml_tpu.parallel.fixed import ShardMapObjective

            objective = ShardMapObjective(objective, self.mesh)
        self._objective = objective
        box = _box_from_constraints(
            self.config.constraints, self.dim, self._dtype, self._norm,
            d_pad=self._d_pad if self._fs else None,
            space=self.config.constraint_space)
        solve = make_solver(objective, self.config.optimizer,
                            self.config.solver, box=box)

        # reg is a TRACED argument: a reg-weight grid re-enters this exact
        # compiled program (the optimizer/L1-regime dispatch inside
        # make_solver stays keyed to the build-time reg — see _solver_key).
        # The batch is an argument too (see __init__ compile-time note).
        def _solve(w0: Array, batch, reg: Regularization) -> SolverResult:
            return solve(w0, batch, objective=objective.with_reg(reg))

        # Feature-sharded solves keep w P("feature") end-to-end (propagated
        # from w0) — replicating the output would defeat the sharding.
        out_shard = (replicate(self.mesh)
                     if self.mesh is not None and not self._fs else None)
        self._solve = (jax.jit(_solve, out_shardings=out_shard)
                       if out_shard is not None else jax.jit(_solve))
        self._solver_key = self._make_solver_key()

    def _make_solver_key(self) -> tuple:
        """Everything (besides reg VALUES) that shapes the compiled solver."""
        c = self.config
        return (c.optimizer, c.solver, c.reg.l1 > 0.0, c.variance,
                c.intercept_index, c.constraints, c.constraint_space)

    def data_key(self) -> tuple:
        """Identity of the device data layout (reuse across optimization
        configs — reference GameEstimator prepares datasets once, fit:454-557)."""
        return ("fixed", self.config.feature_shard, self.config.storage_dtype,
                self._fs)

    def rebind(self, config: FixedEffectConfig) -> "FixedEffectCoordinate":
        """New optimization settings over the SAME device-resident data.
        A reg-weight-only change keeps the compiled solver (reg is a traced
        argument of ``_solve``) — zero recompilation across a λ grid."""
        import copy

        if (config.feature_shard != self.config.feature_shard
                or config.storage_dtype != self.config.storage_dtype
                or config.feature_sharded != self.config.feature_sharded):
            raise ValueError("rebind cannot change the feature shard, its "
                             "storage dtype, or feature sharding")
        new = copy.copy(self)
        new.config = config
        if new._make_solver_key() != self._solver_key:
            new._bind_solver()
        return new

    def _pad(self, a: np.ndarray) -> np.ndarray:
        pad = self._padded_n - len(a)
        return a if pad == 0 else np.concatenate([a, np.zeros(pad, a.dtype)])

    def _down_sample_mult(self, keep, y):
        """Per-task sampling rule (reference DownSamplerHelper.scala:33-40):
        binary tasks keep every positive and reweight sampled negatives by
        1/rate (BinaryClassificationDownSampler.scala:32-55); regression
        tasks sample uniformly with NO reweight (DefaultDownSampler)."""
        rate = self.config.down_sampling_rate
        xp = jnp if isinstance(keep, jax.Array) else np
        if self.task in (TaskType.LOGISTIC_REGRESSION,
                         TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
            mult = xp.where(keep, 1.0 / rate, 0.0)
            return xp.where(y > 0.5, 1.0, mult).astype(self._dtype)
        return keep.astype(self._dtype)

    def _down_sample_weights(self, seed: int) -> Array:
        """Host-paced resample-per-update path (reference
        DistributedOptimizationProblem.runWithSampling:159-174)."""
        rate = self.config.down_sampling_rate
        if rate >= 1.0:
            return self._base_weight
        rng = np.random.default_rng(seed)
        keep = rng.random(self._padded_n) < rate
        mult = self._down_sample_mult(keep, np.asarray(self._batch.y))
        return self._base_weight * jnp.asarray(mult)


    def _initial_state(self, init: Optional[FixedEffectModel]) -> Array:
        """Initial transformed-space solver state (cold, or an ORIGINAL-space
        warm-start model mapped in), padded + P("feature")-sharded when the
        coordinate is feature-sharded.  The ONE definition shared by the
        host-paced update() and the fused sweep's init_sweep_state — the
        fused==host parity tests rely on them never drifting."""
        if init is not None:
            means = np.asarray(init.coefficients.means, self._dtype)
            if self._fs and len(means) < self._d_pad:
                means = np.pad(means, (0, self._d_pad - len(means)))
            w = self._norm.model_to_transformed_space(
                jnp.asarray(means), self.config.intercept_index)
        else:
            w = jnp.zeros(self._d_pad, self._dtype)  # _d_pad == dim unless _fs
        if self._fs:
            from photon_ml_tpu.parallel.mesh import shard_coefficients

            w = shard_coefficients(w, self.mesh)
        return w

    def update(self, total_offsets: np.ndarray, seed: int = 0,
               init: Optional[FixedEffectModel] = None) -> Tuple[FixedEffectModel, SolverResult]:
        """Solve in TRANSFORMED space, publish the model in ORIGINAL space
        (reference Optimizer.optimize:175 modelToTransformedSpace on entry,
        GeneralizedLinearOptimizationProblem.createModel original-space exit;
        NormalizationContext.scala:73-124).  Models/scores everywhere else are
        original-space, so warm starts convert back in."""
        ii = self.config.intercept_index
        w0 = self._initial_state(init)
        offs = jnp.asarray(self._pad(np.asarray(total_offsets, self._dtype)))
        weights = self._down_sample_weights(seed)
        res = self._solve(w0, self._batch.replace(offset=offs, weight=weights),
                          self.config.reg)
        w_orig = self._norm.model_to_original_space(res.w, ii)
        variances = None
        if self.config.variance != VarianceComputationType.NONE:
            # Computed at the optimization-space coefficients, then mapped
            # through the SAME coefficient transform as the means — exact
            # reference behavior (DistributedOptimizationProblem.scala:84-108;
            # GeneralizedLinearOptimizationProblem.createModel:89-95 applies
            # modelToOriginalSpace to the variances vector verbatim).
            from photon_ml_tpu.opt.solve import compute_variances

            v = compute_variances(
                self._objective.with_reg(self.config.reg), res.w,
                self._batch.replace(offset=offs, weight=weights),
                self.config.variance)
            variances = np.asarray(self._norm.model_to_original_space(v, ii))
            variances = variances[: self.dim]
        model = FixedEffectModel(
            coefficients=Coefficients(means=np.asarray(w_orig)[: self.dim],
                                      variances=variances),
            feature_shard=self.config.feature_shard,
            task=self.task,
        )
        return model, res

    def score(self, model: FixedEffectModel) -> np.ndarray:
        means = np.asarray(model.coefficients.means, self._dtype)
        if self._fs and len(means) < self._d_pad:
            means = np.pad(means, (0, self._d_pad - len(means)))
        s = self._score(jnp.asarray(means), self._batch)
        return np.asarray(s)[: self._n]

    def tracker_summary(self, tracker) -> dict:
        """Solver telemetry for the job log (FixedEffectOptimizationTracker)."""
        from photon_ml_tpu.opt.types import summarize_solver_results

        return summarize_solver_results(tracker)

    # --- traceable-step interface (game/fused.py) ---
    # State = transformed-space coefficient vector [d].

    def init_sweep_state(self, init: Optional[FixedEffectModel] = None) -> Array:
        """Sweep state = transformed-space coefficients.  Feature-sharded
        coordinates carry a P("feature")-sharded [d_pad] state through the
        scanned program — the residual fold only ever consumes the [n]-vector
        scores (already feature-axis-reduced by trace_update), so the fused
        descent runs one program for every model size, like the reference's
        single CoordinateDescent path (CoordinateDescent.scala:93-107)."""
        return self._initial_state(init)

    def sweep_data(self):
        """The batch enters the fused program as an ARGUMENT (compile-time
        note in __init__)."""
        return self._batch

    def _sweep_batch_inputs(self, offsets: Array, key, batch) -> Tuple[Array, Array]:
        """(padded offsets, per-update weights) — the ONE definition of what a
        sweep update sees; trace_update and trace_variances must agree on it
        (down-sampled weights are re-drawn from the same key, so XLA CSEs the
        duplicate draw and the variance weights match the update's exactly)."""
        pad = self._padded_n - self._n
        offs = (jnp.pad(offsets, (0, pad)) if pad else offsets).astype(self._dtype)
        if self.config.down_sampling_rate < 1.0 and key is not None:
            keep = (jax.random.uniform(key, (self._padded_n,))
                    < self.config.down_sampling_rate)
            return offs, batch.weight * self._down_sample_mult(keep, batch.y)
        return offs, batch.weight

    def trace_update(self, state: Array, offsets: Array,
                     reg: Optional[Regularization] = None,
                     key=None, data=None) -> Tuple[Array, Array]:
        batch = self._batch if data is None else data
        offs, weights = self._sweep_batch_inputs(offsets, key, batch)
        res = self._solve(state, batch.replace(offset=offs, weight=weights),
                          self.config.reg if reg is None else reg)
        w_pub = self.trace_publish(res.w)
        if self._fs and isinstance(batch, SparseBatch):
            # pinned communication: one [n_local] feature-axis psum instead
            # of GSPMD all-gathering the full sharded coefficient vector
            return res.w, self._objective.margins(w_pub, batch)[: self._n]
        return res.w, batch.margins(w_pub)[: self._n]

    def trace_publish(self, state: Array, data=None) -> Array:
        return self._norm.model_to_original_space(state,
                                                  self.config.intercept_index)

    def export_model(self, published: np.ndarray) -> FixedEffectModel:
        return FixedEffectModel(
            coefficients=Coefficients(
                means=np.asarray(published)[: self.dim]),
            feature_shard=self.config.feature_shard, task=self.task)

    def init_sweep_variances(self) -> Array:
        if self.config.variance == VarianceComputationType.NONE:
            return jnp.zeros(0, self._dtype)
        v = jnp.zeros(self._d_pad if self._fs else self.dim, self._dtype)
        if self._fs:
            from photon_ml_tpu.parallel.mesh import shard_coefficients

            v = shard_coefficients(v, self.mesh)
        return v

    def trace_variances(self, state: Array, offsets: Array,
                        reg: Optional[Regularization] = None,
                        key=None, data=None) -> Array:
        """Traced coefficient variances at this update's iterate against this
        update's offsets, (down-sampled) weights AND traced ``reg`` — the
        exact inputs trace_update solved with, so the last iteration's values
        match what the host path publishes
        (DistributedOptimizationProblem.scala:84-108: variances are computed
        per update; only the final update's survive into the model)."""
        from photon_ml_tpu.opt.solve import compute_variances

        batch = self._batch if data is None else data
        offs, weights = self._sweep_batch_inputs(offsets, key, batch)
        v = compute_variances(
            self._objective.with_reg(self.config.reg if reg is None else reg),
            state, batch.replace(offset=offs, weight=weights),
            self.config.variance)
        return self._norm.model_to_original_space(v, self.config.intercept_index)

    def export_variances(self, v) -> np.ndarray:
        return np.asarray(v)[: self.dim]

    # --- external (validation) scoring (fused validated sweeps) ---------

    def external_data(self, data: GameData):
        """Held-out design for this shard, device-resident once (dense
        [n, d] or the SparseShard COO pair) — the same layout
        FixedEffectModel.score consumes."""
        from photon_ml_tpu.utils.transfer import chunked_device_put

        shard = data.features[self.config.feature_shard]
        if isinstance(shard, SparseShard):
            return {"x_idx": chunked_device_put(shard.indices, np.int32),
                    "x_val": chunked_device_put(shard.values, self._dtype)}
        return {"x": chunked_device_put(np.asarray(shard), self._dtype)}

    def trace_score_external(self, published: Array, vdata) -> Array:
        """== FixedEffectModel.score: x @ w (dense) or the gather-einsum
        (sparse), on the ORIGINAL-space published coefficients."""
        w = published[: self.dim]
        if "x" in vdata:
            return vdata["x"] @ w
        return jnp.einsum("nk,nk->n", vdata["x_val"], w[vdata["x_idx"]])


def _box_from_constraints(constraints, dim: int, dtype, norm=None,
                          d_pad: Optional[int] = None,
                          space: str = "original"):
    """(lower, upper) solver box arrays in the SOLVE (transformed) space.

    Reference: OptimizerConfig.constraintMap (OptimizerConfig.scala:47)
    applied by OptimizationUtils.projectCoefficientsToSubspace per iteration
    — here the bounds become the LBFGS projected-gradient box
    (opt/lbfgs.py:97 via make_solver(box=...)).

    ``space="original"`` (default): bounds constrain the PUBLISHED
    original-space coefficients; with scaling normalization
    w_orig = factors * w_t (factors > 0) the transformed-space box is
    [lo/f, hi/f], and shift normalization is refused loudly (the
    -<w, shifts> intercept fold makes per-feature original bounds
    non-separable).

    ``space="transformed"``: reference-compat — raw bounds applied to the
    transformed-space iterate regardless of normalization, reproducing
    TRON.scala:228 / OptimizationUtils.scala:56-58 (which silently apply
    original-space constraintMap bounds in the scaled+shifted space); the
    published original-space coefficients can then violate the written
    bounds.  See game/config._canonicalize_constraints and MIGRATION.md.
    """
    if not constraints:
        return None
    if space == "transformed":
        norm = None  # raw bounds in solver space: the reference's behavior
    total = d_pad or dim
    lo = np.full(total, -np.inf, dtype)
    hi = np.full(total, np.inf, dtype)
    if total != dim:
        lo[dim:] = 0.0  # padded coefficient slots stay pinned at 0
        hi[dim:] = 0.0
    for j, l, h in constraints:
        if not 0 <= j < dim:
            raise ValueError(
                f"constraint feature index {j} out of range [0, {dim})")
        lo[j], hi[j] = l, h
    if norm is not None:
        if norm.shifts is not None:
            raise ValueError(
                "box constraints with shift normalization are not supported "
                "(original-space bounds are non-separable under shifts); use "
                "a scaling-only normalization type, or "
                "constraint_space='transformed' for reference-compat raw "
                "bounds on the transformed iterate (MIGRATION.md)")
        if norm.factors is not None:
            f = np.asarray(norm.factors)
            lo, hi = lo / f, hi / f
    return jnp.asarray(lo), jnp.asarray(hi)


def _re_data_key(c: RandomEffectConfig) -> tuple:
    """Every field that affects the DATA layout (buckets + projection); a
    config differing only in optimization settings may reuse device arrays."""
    return ("random", c.random_effect_type, c.feature_shard, c.active_cap,
            c.min_active_samples, c.projector, c.projected_dim,
            c.features_to_samples_ratio, c.intercept_index, c.storage_dtype)


class RandomEffectCoordinate(Coordinate):
    """Per-entity GLM coordinate (reference RandomEffectCoordinate.scala:39-232).

    All entities are bucketed once at construction; every update solves every
    bucket with a vmapped jitted solver.  Scoring covers ALL samples —
    including those capped out of the active set — via the stacked-coefficient
    gather (the reference's passive-data path).
    """

    def __init__(self, coordinate_id: str, data: GameData, config: RandomEffectConfig,
                 task: TaskType, mesh: Optional[Mesh] = None, seed: int = 0,
                 dtype=np.float32, norm: Optional[NormalizationContext] = None,
                 existing_model_keys: Optional[frozenset] = None):
        self.coordinate_id = coordinate_id
        self.config = config
        self.task = task
        self.mesh = mesh
        self._n = data.num_samples
        self._dtype = dtype
        self.dim = data.shard_dim(config.feature_shard)
        # Per-entity normalization (reference: one NormalizationContext per
        # REId — NormalizationContextRDD, RandomEffectOptimizationProblem
        # .scala:154-178, built by GameEstimator.prepareNormalizationContext
        # Wrappers:646-680).  Three cases, exactly the reference's:
        #   IDENTITY projector  -> ONE shared context for every entity
        #                          (NormalizationContextBroadcast);
        #   INDEX_MAP projector -> the coordinate context PROJECTED into each
        #                          entity's compact space (the RDD case) —
        #                          here: per-lane gathered factor arrays that
        #                          ride the vmapped solve as traced leaves;
        #   RANDOM projector    -> the context pushed through the Gaussian
        #                          matrix, shared by every entity (reference
        #                          ProjectionMatrixBroadcast
        #                          .projectNormalizationContext:102-112);
        #                          shifts need the intercept pass-through
        #                          slot (intercept_index set).
        if (norm is not None and norm.shifts is not None
                and config.projector == ProjectorType.RANDOM
                and config.intercept_index is None):
            raise ValueError(
                f"coordinate {coordinate_id!r}: shift normalization under a "
                "RANDOM projection needs intercept_index — the Gaussian "
                "matrix then carries the reference's intercept pass-through "
                "slot (ProjectionMatrix.scala:112-120)")
        self._norm = None
        if norm is not None and (norm.factors is not None
                                 or norm.shifts is not None):
            self._norm = norm.replace(
                factors=None if norm.factors is None
                else jnp.asarray(norm.factors, dtype),
                shifts=None if norm.shifts is None
                else jnp.asarray(norm.shifts, dtype))
        self._base_offset = np.asarray(data.offset, np.float64)

        shard_data = data.features[config.feature_shard]
        entity_ids = data.id_tags[config.random_effect_type]
        lane_multiple = int(np.prod(list(mesh.shape.values()))) if mesh is not None else 1
        self._sparse = isinstance(shard_data, SparseShard)
        if (self._norm is not None and self._norm.shifts is not None
                and config.intercept_index is None
                and (self._sparse
                     or config.projector == ProjectorType.INDEX_MAP)):
            # Shift normalization under observed-column compaction projects
            # the context per entity, exactly like the reference's per-REId
            # NormalizationContextRDD through its per-entity projectors
            # (IndexMapProjectorRDD.scala:34-262): the intercept is observed
            # in every active sample, so compaction keeps a per-entity
            # intercept column whose per-lane position absorbs the margin
            # shift — but the coordinate must know WHICH full-dim column
            # that is.
            raise ValueError(
                f"coordinate {coordinate_id!r}: shift normalization under "
                "per-entity compaction needs intercept_index (the per-lane "
                "intercept column absorbs the projected margin shift)")
        if self._sparse:
            # Row-sparse RE feature bag (the reference's per-entity sparse
            # LocalDataset, data/LocalDataset.scala:35-247): each entity
            # solves in the compact space of its observed columns, built
            # DIRECTLY from the sparse rows — the full-vocabulary [E, S, d]
            # bucket tensors never exist (bucket_by_entity_sparse).
            # (projected_dim without RANDOM is rejected at CONFIG time —
            # RandomEffectConfig.__post_init__ — so no guard here)
            from photon_ml_tpu.parallel.bucketing import bucket_by_entity_sparse
            from photon_ml_tpu.parallel.projection import ProjectedBuckets

            ratio = (config.features_to_samples_ratio
                     if config.projector == ProjectorType.INDEX_MAP else None)
            self.buckets, projections = bucket_by_entity_sparse(
                entity_ids, shard_data.indices, shard_data.values, self.dim,
                np.asarray(data.y, dtype),
                offset=np.asarray(data.offset, dtype),
                weight=np.asarray(data.weight, dtype),
                active_cap=config.active_cap,
                min_active_samples=config.min_active_samples,
                lane_multiple=lane_multiple, seed=seed, dtype=dtype,
                features_to_samples_ratio=ratio,
                intercept_index=config.intercept_index,
                existing_model_keys=existing_model_keys,
            )
            self._proj = ProjectedBuckets(base=self.buckets,
                                          buckets=self.buckets.buckets,
                                          projections=projections)
            if config.projector == ProjectorType.RANDOM:
                # RANDOM over a sparse shard: the shared Gaussian matrix's
                # rows GATHERED through each lane's observed-column map
                # project the compact design into d_proj — exactly what the
                # densified x @ A computes, because unobserved columns
                # contribute zero either way (reference builds the same
                # shared matrix per coordinate, ProjectionMatrixBroadcast
                # .scala:150; the full-vocabulary [E, S, d] tensors still
                # never exist).
                import dataclasses as _dc

                from photon_ml_tpu.parallel.projection import (
                    build_random_projection)

                if config.projected_dim is None:
                    raise ValueError("RANDOM projection requires projected_dim")
                shared = build_random_projection(
                    self.dim, config.projected_dim, seed, dtype=dtype,
                    intercept_index=config.intercept_index)
                proj_buckets = []
                for b, p in zip(self.buckets.buckets, projections):
                    safe = np.where(p.indices < 0, 0, p.indices)
                    a_sub = shared.matrix[safe]  # [lanes, d_compact, d_proj]
                    a_sub = np.where((p.indices >= 0)[:, :, None], a_sub, 0.0)
                    x_proj = np.einsum("lsd,ldp->lsp", b.x,
                                       a_sub).astype(dtype)
                    proj_buckets.append(_dc.replace(b, x=x_proj))
                self._proj = ProjectedBuckets(
                    base=self.buckets, buckets=proj_buckets,
                    projections=[shared] * len(proj_buckets))
        else:
            # A streamed (device-assembled) dense shard stays on device: the
            # bucketer gathers lanes on device, and the [n, d] array never
            # materializes on host — the point of out-of-core ingest.
            shard_is_device = isinstance(shard_data, jax.Array)
            if shard_is_device and config.projector != ProjectorType.IDENTITY:
                raise NotImplementedError(
                    f"coordinate {coordinate_id!r}: projector "
                    f"{config.projector.name} over a device-assembled "
                    "(streamed) design shard would host-materialize it; "
                    "IDENTITY only for now (ROADMAP item 5 follow-on)")
            x = shard_data if shard_is_device else np.asarray(shard_data, dtype)
            groups = None
            if data.entity_stats is not None:
                stats = data.entity_stats.get(config.random_effect_type)
                if stats is not None:
                    # per-entity grouping accumulated chunk-by-chunk during
                    # streaming ingest; None on cap/seed mismatch -> the
                    # bucketer rescans the host id column as usual
                    groups = stats.groups(config.active_cap,
                                          config.min_active_samples, seed,
                                          existing_model_keys)
            self.buckets = bucket_by_entity(
                entity_ids, x, np.asarray(data.y, dtype),
                offset=np.asarray(data.offset, dtype),
                weight=np.asarray(data.weight, dtype),
                active_cap=config.active_cap,
                min_active_samples=config.min_active_samples,
                lane_multiple=lane_multiple,
                seed=seed, dtype=dtype,
                existing_model_keys=existing_model_keys,
                groups=groups,
            )
        # slot order for the stacked model = sorted entity id (stacked_coefficients)
        self._sorted_ids = sorted(self.buckets.lane_of)
        self._slot_of = {eid: i for i, eid in enumerate(self._sorted_ids)}
        # per-bucket lane -> stacked-model row; invalid lanes get an
        # out-of-range index so device scatters drop them (stack_bucket_lanes)
        ne = len(self._sorted_ids)
        self._slot_idx_dev = [
            jnp.asarray(np.where(
                (s := _slots_from(self._slot_of,
                                  np.asarray(b.entity_lanes, np.int64))) < 0,
                ne, s).astype(np.int32))
            for b in self.buckets.buckets
        ]
        self._entity_ids = np.asarray(entity_ids, np.int64)
        self._sample_slots = jnp.asarray(_slots_from(self._slot_of, self._entity_ids))
        # full-sample arrays are the random-effect coordinate's giant
        # host->device transfer — bounded-RPC chunked like the fixed effect's
        from photon_ml_tpu.utils.transfer import chunked_device_put
        self._x_full_is_t = False
        if self._sparse:
            # full-sample scoring stays sparse: [n, k] gather arrays, never
            # an [n, d_full] densified design (score_samples_sparse)
            self._x_idx_dev = chunked_device_put(shard_data.indices, np.int32)
            self._x_val_dev = chunked_device_put(shard_data.values, dtype)
        else:
            # Narrow shards whose padded [n, d] footprint threatens HBM
            # upload TRANSPOSED [d, n]: TPU tiling pads the minor axis to
            # 128 lanes, so a [n, d<=32] array (and every scoring gather
            # from it) occupies 128/d x its logical HBM bytes — 32x at
            # glmix_chip's d=4, an OOM at 8.39M samples.  Small shards keep
            # the row layout: the chip-measured crossover lives with
            # score_samples_t in parallel/bucketing.py.
            from photon_ml_tpu.parallel.bucketing import use_transposed_scoring
            self._x_full_is_t = use_transposed_scoring(
                x.shape[0], x.shape[1], np.dtype(dtype).itemsize)
            self._x_full = chunked_device_put(x.T if self._x_full_is_t else x)

        # Optional per-entity feature projection (reference
        # RandomEffectCoordinateInProjectedSpace.scala:149): solve each bucket
        # in a compact feature space, back-project coefficients to full dim.
        # (A sparse shard arrives here with self._proj already built — its
        # buckets ARE the compact space.)
        if not self._sparse:
            self._proj = None
            if config.projector != ProjectorType.IDENTITY:
                from photon_ml_tpu.parallel.projection import project_buckets

                self._proj = project_buckets(
                    self.buckets, config.projector,
                    projected_dim=config.projected_dim,
                    features_to_samples_ratio=config.features_to_samples_ratio,
                    intercept_index=config.intercept_index,
                    seed=seed,
                )
        solve_buckets = (self._proj.buckets if self._proj is not None
                         else self.buckets.buckets)
        if self._proj is not None:
            # Device twins of each bucket's back-projection (gather indices /
            # shared Gaussian matrix); they travel through sweep_data() into
            # the fused program as arguments.  The Gaussian matrix is SHARED
            # across buckets — upload it once, not once per bucket.
            from photon_ml_tpu.parallel.projection import BucketProjection

            # kinds are STATIC (python strings can't be jit-arg leaves);
            # the arrays are the traced half
            matrix_dev: Dict[int, Array] = {}
            self._proj_kinds = []
            self._proj_dev = []
            for p in self._proj.projections:
                if isinstance(p, BucketProjection):
                    self._proj_kinds.append("index")
                    self._proj_dev.append(jnp.asarray(p.indices))
                else:
                    self._proj_kinds.append("random")
                    key = id(p.matrix)
                    if key not in matrix_dev:  # one upload for the shared matrix
                        matrix_dev[key] = jnp.asarray(p.matrix)
                    self._proj_dev.append(matrix_dev[key])
            self._proj_dev = tuple(self._proj_dev)

        self._bind_solver()
        self._refresh_lane_mult()

        # Device-resident bucket arrays, entity lane sharded over ALL mesh
        # devices (the reference's balanced entity partitioner,
        # RandomEffectDatasetPartitioner.scala:30-171).
        def put(a):
            if mesh is None:
                # single-device: bucket design tensors can be large — use the
                # bounded-RPC chunked transfer (utils/transfer.py), which
                # passes already-device-resident arrays straight through
                return chunked_device_put(a)
            a = jnp.asarray(a)
            spec = PartitionSpec(tuple(mesh.axis_names), *([None] * (a.ndim - 1)))
            return jax.device_put(a, NamedSharding(mesh, spec))

        self._put_entity = put
        sd = _storage_np_dtype(self.config.storage_dtype)  # host-side cast:
        # transfer + HBM residency are storage-width from the start

        def _narrow(bx):
            if sd is None:
                return bx
            if isinstance(bx, jax.Array):
                # streamed shard: bucket tensors are already device-resident;
                # cast on device (transiently double-width, then freed)
                return bx.astype(sd)
            return np.asarray(bx).astype(sd)

        self._dev = [
            dict(x=put(_narrow(b.x)),
                 y=put(b.y), w=put(b.weight),
                 rows=put(np.where(b.rows < 0, 0, b.rows)),
                 valid=put(b.rows >= 0))
            for b in solve_buckets
        ]
        # INDEX_MAP/sparse + normalization: project the coordinate context
        # into each entity's compact space (the reference's per-REId
        # contexts, NormalizationContextRDD through the per-entity
        # projectors, IndexMapProjectorRDD.scala:34-262) — gather the factor
        # AND shift vectors through every lane's column map; padded slots get
        # the identity factor 1 / shift 0.  Shift normalization additionally
        # tracks each lane's compact-space INTERCEPT position: the intercept
        # is observed in every active sample, so compaction keeps it, and the
        # per-lane coefficient-space maps fold the margin shift into it.
        # (RANDOM instead shares ONE projected context, baked by
        # _bind_solver.)
        self._norm_fac_dev = None
        self._norm_shift_dev = None
        self._norm_ii_dev = None
        if self._norm_per_lane:
            from photon_ml_tpu.parallel.projection import BucketProjection

            fac = (np.asarray(self._norm.factors, self._dtype)
                   if self._norm.factors is not None
                   else np.ones(self.dim, self._dtype))
            sh = (np.asarray(self._norm.shifts, self._dtype)
                  if self._norm.shifts is not None else None)
            ii = self.config.intercept_index
            lanes_fac, lanes_sh, lanes_ii = [], [], []
            for p, b in zip(self._proj.projections, self.buckets.buckets):
                assert isinstance(p, BucketProjection)
                safe = np.where(p.indices < 0, 0, p.indices)
                obs = p.indices >= 0
                lanes_fac.append(np.where(obs, fac[safe],
                                          1.0).astype(self._dtype))
                if sh is not None:
                    lanes_sh.append(np.where(obs, sh[safe],
                                             0.0).astype(self._dtype))
                    has_ii = np.any(p.indices == ii, axis=1)
                    valid = np.asarray(b.entity_lanes) >= 0
                    if not np.all(has_ii[valid]):
                        raise ValueError(
                            f"coordinate {self.coordinate_id!r}: shift "
                            "normalization under compaction requires the "
                            "intercept column (feature "
                            f"{ii}) observed in every entity's active "
                            "samples, but some entity never observes it")
                    lanes_ii.append(np.argmax(p.indices == ii,
                                              axis=1).astype(np.int32))
            self._norm_fac_np = lanes_fac  # host twins for warm starts
            self._norm_fac_dev = [put(f) for f in lanes_fac]
            if sh is not None:
                self._norm_shift_np = lanes_sh
                self._norm_ii_np = lanes_ii
                self._norm_shift_dev = [put(s) for s in lanes_sh]
                self._norm_ii_dev = [put(i) for i in lanes_ii]

    def _bind_solver(self) -> None:
        # shared-context normalization (IDENTITY projector) bakes into the
        # objective; per-lane contexts (INDEX_MAP, and any sparse shard —
        # whose solve space is always compact) enter the vmapped solve as
        # traced factor arrays instead (see _vsolve below); a RANDOM
        # projection shares ONE context pushed through the Gaussian matrix
        # (reference ProjectionMatrixBroadcast
        # .projectNormalizationContext:102-112), baked like IDENTITY's
        shared_norm = (self._norm if self._norm is not None
                       and self.config.projector == ProjectorType.IDENTITY
                       and not self._sparse
                       else None)
        self._norm_proj = None
        self._norm_proj_intercept = None
        if (self._norm is not None
                and self.config.projector == ProjectorType.RANDOM):
            rp = self._proj.projections[0]  # shared across buckets
            ctx, p_ii = rp.project_normalization(self._norm)
            self._norm_proj = NormalizationContext(
                factors=None if ctx.factors is None
                else jnp.asarray(ctx.factors, self._dtype),
                shifts=None if ctx.shifts is None
                else jnp.asarray(ctx.shifts, self._dtype))
            self._norm_proj_intercept = p_ii
            shared_norm = self._norm_proj
        objective = GLMObjective(loss=loss_for_task(self.task), reg=self.config.reg,
                                 norm=shared_norm or no_normalization())
        self._objective = objective
        self._norm_per_lane = (self._norm is not None and shared_norm is None)
        box = None
        self._box_lanes = None  # per-bucket (lo, hi) [lanes, d_compact] pairs
        self._box_fill = None   # [dim] publish value for unobserved features
        if self.config.constraints:
            compact = (self._sparse
                       or self.config.projector == ProjectorType.INDEX_MAP)
            if self.config.projector == ProjectorType.RANDOM:
                raise ValueError(
                    f"coordinate {self.coordinate_id!r}: box constraints have "
                    "no meaning in a RANDOM-projected solve space (the "
                    "Gaussian matrix mixes features); use IDENTITY or "
                    "INDEX_MAP")
            if not compact:
                box = _box_from_constraints(self.config.constraints, self.dim,
                                            self._dtype, self._norm,
                                            space=self.config.constraint_space)
            else:
                # Compact solve spaces get PER-LANE bounds: the full-space
                # original bounds gathered through each lane's observed-column
                # map (the reference applies its constraintMap in full
                # coefficient space regardless of storage,
                # OptimizationUtils.projectCoefficientsToSubspace; the compact
                # twin of that is bound-per-observed-column).  Padded slots
                # pin to [0, 0].  Unobserved features publish clip(0, lo, hi)
                # — the full-space box optimum of the L2 pull toward 0 —
                # via the back-projection fill.
                from photon_ml_tpu.opt.solve import check_box_support

                check_box_support(self.config.optimizer,
                                  self.config.reg.l1 > 0.0)
                if self._norm is not None and self._norm.shifts is not None:
                    # constraint_space="transformed" does NOT lift this:
                    # a compact solve publishes through per-lane original-
                    # space maps whose intercept fold would have to include
                    # the unobserved-column fill values to match the
                    # reference's full-space semantics — refusing is the
                    # honest call on both settings (MIGRATION.md)
                    raise ValueError(
                        f"coordinate {self.coordinate_id!r}: box constraints "
                        "with shift normalization are not supported under "
                        "compaction (original-space bounds are non-separable "
                        "under shifts; the constraint_space='transformed' "
                        "compat flag covers non-compact coordinates only)")
                if (self.config.constraint_space == "transformed"
                        and self._norm is not None):
                    # scaling-only compact: the per-lane solve applies
                    # bounds with ORIGINAL semantics (lane-factor division
                    # + original-space publish fill) — silently accepting
                    # the flag here would produce exactly the divergence it
                    # exists to prevent
                    raise ValueError(
                        f"coordinate {self.coordinate_id!r}: "
                        "constraint_space='transformed' is not supported "
                        "for compact (sparse/INDEX_MAP) solves under "
                        "normalization — use the IDENTITY projector for "
                        "reference-compat constrained coordinates "
                        "(MIGRATION.md)")
                lo, hi = _box_from_constraints(self.config.constraints,
                                               self.dim, self._dtype)
                lo, hi = np.asarray(lo), np.asarray(hi)
                self._box_fill = np.clip(0.0, lo, hi).astype(self._dtype)
                lanes_box = []
                for p in self._proj.projections:
                    safe = np.where(p.indices < 0, 0, p.indices)
                    lo_c = np.where(p.indices >= 0, lo[safe],
                                    0.0).astype(self._dtype)
                    hi_c = np.where(p.indices >= 0, hi[safe],
                                    0.0).astype(self._dtype)
                    lanes_box.append((jnp.asarray(lo_c), jnp.asarray(hi_c)))
                self._box_lanes = lanes_box
        solve = make_solver(objective, self.config.optimizer,
                            self.config.solver, box=box)

        # reg traced PER LANE (vmapped like the data): λ sweeps reuse this
        # compilation, and per-entity regularization costs nothing extra.
        # Optional per-lane extras ride the same vmap, in a fixed order:
        # normalization factor rows (per-lane contexts), then box lo/hi rows
        # (compact-space constrained solves) — _solve_extras builds the
        # matching argument tuple.
        per_lane_norm = self._norm_per_lane
        per_lane_shift = (per_lane_norm and self._norm.shifts is not None)
        per_lane_box = self._box_lanes is not None

        def _one(w, xx, yy, oo, ww, rr, *ex):
            i = 0
            obj = objective.with_reg(rr)
            fa = None
            if per_lane_norm:
                fa = ex[i]
                i += 1
                sh = None
                if per_lane_shift:
                    sh = ex[i]
                    i += 1
                obj = obj.replace(
                    norm=NormalizationContext(factors=fa, shifts=sh))
            kw = {}
            if per_lane_box:
                lo_r, hi_r = ex[i], ex[i + 1]
                if fa is not None:  # original-space bounds -> solve space
                    lo_r, hi_r = lo_r / fa, hi_r / fa
                kw["box"] = (lo_r, hi_r)
            return solve(w, DenseBatch(x=xx, y=yy, offset=oo, weight=ww),
                         objective=obj, **kw)

        def _vsolve(w0, x_b, y_b, off_b, wt_b, reg, *extras_b):
            return jax.vmap(_one)(w0, x_b, y_b, off_b, wt_b, reg, *extras_b)

        self._vsolve = jax.jit(_vsolve)

        # Narrow dense lanes swap in the structure-of-arrays Newton solver:
        # the vmapped path's [lanes, d] / [lanes, m, d] solver state pads
        # its trailing axis to 128 TPU lanes (32x HBM at d=4 — profiled as
        # 63% of the glmix_chip sweep), while the [d, lanes] Newton state
        # pads at most 2x and converges in a fraction of the iterations.
        # Same strictly convex objective, same convergence contract, same
        # optimum to solver tolerance (opt/newton_soa.py; parity-tested).
        # The bucket device arrays keep their [lanes, ...] layout — the
        # transpose below reads them once per solve call, not per solver
        # iteration — so the variance path and bucket plumbing are
        # untouched.
        from photon_ml_tpu.opt.newton_soa import (soa_eligible,
                                                  solve_newton_soa)

        # The swap wins where the vmapped path's 128-lane padding waste
        # dominates (tiny d, modest caps, many lanes); at larger d/cap the
        # Hessian assembly (d^2/2 weighted column products over the cap)
        # outweighs it.  Measured on a real v5e (BENCH artifacts, round 5):
        # glmix_chip (d=4, cap 32, 131k lanes) 2.7x FASTER; glmix2 (d=16,
        # cap 256, 2k lanes) 1.5x SLOWER.  cap*d^2/2 <= 1280 keeps the
        # winning regime: per-iteration Hessian traffic at or below the
        # vmapped path's padded-state traffic (128 lanes x m=10 history).
        # The SOLVE-space shapes decide: compact sparse buckets and
        # projected (INDEX_MAP / RANDOM) buckets solve at their compact /
        # projected width, which is exactly where narrow dims live — the
        # back-projection and publish plumbing run on res.w and are
        # solver-agnostic.
        solve_shapes = [
            (b.x.shape[1], b.x.shape[2])
            for b in (self._proj.buckets if self._proj is not None
                      else self.buckets.buckets)]
        worst = max((cap * dd * dd for cap, dd in solve_shapes), default=0)
        max_solve_dim = max((dd for _, dd in solve_shapes), default=0)
        self._use_soa = (
            soa_eligible(max_solve_dim, objective.loss.name)
            and worst <= 2 * 1280
            and self._norm is None
            and box is None and self._box_lanes is None
            and not self.config.constraints
            and self.config.reg.l1 == 0.0
            and self.config.optimizer in (OptimizerType.LBFGS,
                                          OptimizerType.TRON))
        if self._use_soa:
            solver_cfg = self.config.solver

            def _vsolve_soa(w0, x_b, y_b, off_b, wt_b, reg):
                res = solve_newton_soa(
                    objective.loss, jnp.transpose(w0),
                    jnp.transpose(x_b, (1, 2, 0)), jnp.transpose(y_b),
                    jnp.transpose(off_b), jnp.transpose(wt_b), reg.l2,
                    solver_cfg)
                return res.replace(w=jnp.transpose(res.w))

            self._vsolve = jax.jit(_vsolve_soa)

        kind = self.config.variance
        # BOTH variance kinds are EXACT under observed-column compaction
        # (sparse shards / INDEX_MAP): an unobserved feature's column is
        # identically zero in this entity's data, so the full-space Hessian
        # H = Σ w·l''·x xᵀ + λ2 I is BLOCK-DIAGONAL — the observed block is
        # the compact Hessian and the unobserved block is exactly λ2 I with
        # no cross terms.  Hence SIMPLE (1/diag H) and FULL (diag H⁻¹) both
        # decompose: observed features from the compact computation,
        # unobserved features prior-only 1/λ2.  RANDOM mixes features, so
        # neither is exact there (refused below, as in _bind_solver's
        # RANDOM-variance guard).
        self._compact_variances = (kind != VarianceComputationType.NONE
                                   and (self._sparse or self.config.projector
                                        == ProjectorType.INDEX_MAP))
        if kind != VarianceComputationType.NONE:
            if self.config.projector == ProjectorType.RANDOM:
                raise ValueError(
                    "per-entity variances are not defined under a RANDOM "
                    "projection (the Gaussian matrix mixes features); use "
                    "IDENTITY or INDEX_MAP "
                    f"(coordinate {self.coordinate_id!r})")
            if self._compact_variances and self._norm is not None:
                raise NotImplementedError(
                    "coefficient variances under compaction do not support "
                    "per-entity normalization contexts — drop the "
                    "normalization or use an uncompacted (IDENTITY, dense) "
                    f"layout (coordinate {self.coordinate_id!r})")
            from photon_ml_tpu.opt.solve import compute_variances

            def _vvar(w_b, x_b, y_b, off_b, wt_b, reg):
                return jax.vmap(
                    lambda w, xx, yy, oo, ww, rr: compute_variances(
                        objective.with_reg(rr), w,
                        DenseBatch(x=xx, y=yy, offset=oo, weight=ww), kind)
                )(w_b, x_b, y_b, off_b, wt_b, reg)

            self._vvar = jax.jit(_vvar)
        else:
            self._vvar = None
        self._solver_key = self._make_solver_key()

    def _expand_compact_variances(self, v_compact: Array, bucket_index: int,
                                  lane_reg: Regularization) -> Array:
        """[lanes, d_compact] variances -> [lanes, d_full]: observed features
        carry their computed variance, every other feature is prior-only
        1/λ2 (the per-lane effective λ2, so per-entity multipliers are
        honored).  Exact for BOTH kinds: the full-space Hessian is
        block-diagonal (unobserved columns are identically zero in this
        entity's data), its unobserved block exactly λ2 I — so SIMPLE's
        1/diag(H) and FULL's diag(H⁻¹) are each 1/λ2 there, and the observed
        block's computation is untouched by the unobserved one.  NOTE: the NTV model format stores nonzero-MEAN features
        only (reference sparse storage), so prior-only variances live in the
        in-memory/columnar model but do not survive an NTV save — absent
        features reload as variance 0, the format's "not estimated" marker.
        Padded compact slots route OUT of range and drop — a
        'set' scatter with a duplicate target is order-nondeterministic, so
        letting them collide with a genuinely observed column 0 could
        clobber its variance."""
        idxs = self._proj_dev[bucket_index]  # [lanes, d_compact], -1 padding
        lanes = v_compact.shape[0]
        fill = 1.0 / jnp.maximum(
            jnp.broadcast_to(jnp.asarray(lane_reg.l2, v_compact.dtype),
                             (lanes,)), 1e-30)
        out = jnp.broadcast_to(fill[:, None], (lanes, self.dim))
        safe = jnp.where(idxs < 0, self.dim, idxs)  # out-of-range -> dropped
        return out.at[jnp.arange(lanes)[:, None], safe].set(
            v_compact, mode="drop")

    def _make_solver_key(self) -> tuple:
        c = self.config
        return (c.optimizer, c.solver, c.reg.l1 > 0.0, c.variance,
                c.constraints, c.constraint_space)

    def _refresh_lane_mult(self) -> None:
        """Cache per-bucket (ones, multiplier) lane vectors — constant per
        config, rebuilt only when the config changes (rebind)."""
        mult = dict(self.config.per_entity_l2_multipliers or ())
        self._lane_mult = []
        for b in self.buckets.buckets:
            ones = jnp.ones(b.num_lanes, self._dtype)
            if mult:
                m = jnp.asarray(np.asarray(
                    [mult.get(int(e), 1.0) for e in b.entity_lanes],
                    self._dtype))
            else:
                m = ones
            self._lane_mult.append((ones, m))

    def _solve_extras(self, bi: int, data=None) -> tuple:
        """Per-bucket extra vmapped solver arguments, in ``_one``'s fixed
        order: per-lane normalization factor rows, per-lane shift rows, then
        per-lane box lo/hi rows.  ``data``: sweep_data() pytree when tracing
        (fused program argument convention), None for the host-paced path."""
        out = ()
        if self._norm_per_lane:
            out += ((data["norm_fac"] if data is not None
                     else self._norm_fac_dev)[bi],)
            if self._norm.shifts is not None:
                out += ((data["norm_shift"] if data is not None
                         else self._norm_shift_dev)[bi],)
        if self._box_lanes is not None:
            lo, hi = (data["box"] if data is not None
                      else self._box_lanes)[bi]
            out += (lo, hi)
        return out

    def _lane_regs(self, reg: Regularization) -> List[Regularization]:
        """Per-bucket per-lane Regularization pytrees: the scalar (possibly
        traced) ``reg`` broadcast over lanes, L2 scaled by the per-entity
        multipliers (default 1; padded lanes get 1, they're inert anyway)."""
        return [Regularization(l1=reg.l1 * ones, l2=reg.l2 * m)
                for ones, m in self._lane_mult]

    def data_key(self) -> tuple:
        return _re_data_key(self.config)

    def rebind(self, config: RandomEffectConfig) -> "RandomEffectCoordinate":
        """New optimization settings over the SAME buckets/device arrays.
        Reg-weight-only changes keep the compiled vmapped solver."""
        import copy

        if _re_data_key(config) != _re_data_key(self.config):
            raise ValueError("rebind cannot change the data configuration")
        new = copy.copy(self)
        new.config = config
        if new._make_solver_key() != self._solver_key:
            new._bind_solver()
        if config.per_entity_l2_multipliers != self.config.per_entity_l2_multipliers:
            new._refresh_lane_mult()
        return new

    @staticmethod
    def _dense_init(init):
        """Warm-start models arrive in either random-effect container; the
        warm-start gathers below need the dense stack, so a compact model
        densifies HERE (once per update, logged — at true wide-vocabulary
        scale the caller should warm-start selectively instead)."""
        from photon_ml_tpu.models.game import CompactRandomEffectModel

        if isinstance(init, CompactRandomEffectModel):
            import logging

            logging.getLogger("photon_ml_tpu.coordinate").info(
                "densifying a CompactRandomEffectModel warm start "
                "(%d entities x %d features)", init.num_entities, init.dim)
            return init.to_dense()
        return init

    def _warm_start(self, bucket_index: int, init: RandomEffectModel) -> np.ndarray:
        """Full-dim warm-start lanes, projected into the solve space if needed."""
        b = self.buckets.buckets[bucket_index]
        w0 = np.zeros((b.num_lanes, self.dim), self._dtype)
        for lane, eid in enumerate(b.entity_lanes):
            slot = init.slot_of.get(int(eid)) if eid >= 0 else None
            if slot is not None:
                w0[lane] = init.w_stack[slot]
        if self._proj is not None:
            from photon_ml_tpu.parallel.projection import BucketProjection

            proj = self._proj.projections[bucket_index]
            if isinstance(proj, BucketProjection):
                safe = np.where(proj.indices < 0, 0, proj.indices)
                w0 = np.where(proj.indices >= 0,
                              np.take_along_axis(w0, safe, axis=1), 0.0)
            else:
                # Gaussian projection has no exact inverse; restart cold
                # (zeros are zeros under any normalization of the projected
                # space, so no transformed-space mapping applies either)
                return np.zeros((b.num_lanes, proj.d_proj), self._dtype)
        if self._norm is not None:
            # published models are ORIGINAL-space; solves run transformed
            # (same convention as the fixed effect's update())
            if self._norm_per_lane:
                if self._norm.shifts is not None:
                    # per-lane modelToTransformedSpace: the shift dot folds
                    # into each lane's own compact intercept position.
                    # DELIBERATELY the COMPACT dot (observed columns only):
                    # the compact objective computes margins as
                    # <eff, x_c> - <eff, sh_c> (objective.py margin_shift),
                    # i.e. the unobserved-column data term -w_j*shift_j that
                    # would cancel a full-dim fold was deleted by compaction
                    # — folding the full <w, shifts> here would shift every
                    # margin by sum_unobserved(w_j*shift_j).  Warm-start
                    # mass at unobserved columns is margin-inert on this
                    # entity's data (raw x_j == 0 there), so truncating it
                    # is exact, not lossy (advisor r4, resolved r5).
                    sh = self._norm_shift_np[bucket_index]
                    iis = self._norm_ii_np[bucket_index]
                    dots = np.einsum("ld,ld->l", w0, sh)
                    w0[np.arange(len(w0)), iis] += dots
                w0 = w0 / self._norm_fac_np[bucket_index]
            else:
                n = self._norm
                if n.shifts is not None:
                    ii = self.config.intercept_index
                    w0[:, ii] += w0 @ np.asarray(n.shifts)
                if n.factors is not None:
                    w0 = w0 / np.asarray(n.factors)
        return w0.astype(self._dtype)

    def _lanes_to_original(self, lanes: Array, bucket_index: int,
                           data=None) -> Array:
        """Map a bucket's transformed-space lane vectors to original space
        (the reference applies modelToOriginalSpace per entity problem —
        GeneralizedLinearOptimizationProblem.createModel).  ``data``:
        sweep_data() pytree when tracing, None for the host-paced path."""
        if self._norm is None:
            return lanes
        if self._norm_per_lane:
            fac = (data["norm_fac"] if data is not None
                   else self._norm_fac_dev)[bucket_index]
            eff = lanes * fac
            if self._norm.shifts is not None:
                # fold -<eff, shifts> into each lane's OWN intercept column
                # (NormalizationContext.scala:73-99, per projected context)
                sh = (data["norm_shift"] if data is not None
                      else self._norm_shift_dev)[bucket_index]
                ii_l = (data["norm_ii"] if data is not None
                        else self._norm_ii_dev)[bucket_index]
                adj = -jnp.sum(eff * sh, axis=1)
                eff = eff.at[jnp.arange(eff.shape[0]), ii_l].add(adj)
            return eff
        if self._norm_proj is not None:
            # RANDOM projection: the model leaves the solver in the
            # TRANSFORMED PROJECTED space; the projected context (with its
            # pass-through intercept slot) maps it to the original projected
            # space, and back-projection to full dim happens afterwards —
            # the reference order (createModel in projected space, then
            # projectCoefficientsRDD)
            ii = self._norm_proj_intercept
            return jax.vmap(
                lambda w: self._norm_proj.model_to_original_space(w, ii))(lanes)
        ii = self.config.intercept_index
        return jax.vmap(
            lambda w: self._norm.model_to_original_space(w, ii))(lanes)

    def update(self, total_offsets: np.ndarray, seed: int = 0,
               init: Optional[RandomEffectModel] = None
               ) -> Tuple[RandomEffectModel, List[SolverResult]]:
        init = self._dense_init(init)
        offs = jnp.asarray(np.asarray(total_offsets, self._dtype))
        coeffs = []
        variances = [] if self._vvar is not None else None
        results = []
        lane_regs = self._lane_regs(self.config.reg)
        for bi, (b, dev) in enumerate(zip(self.buckets.buckets, self._dev)):
            solve_dim = dev["x"].shape[2]
            if init is not None:
                w0 = self._put_entity(self._warm_start(bi, init))
            else:
                w0 = self._put_entity(np.zeros((b.num_lanes, solve_dim), self._dtype))
            # residual offsets gathered into the bucket layout
            off_b = jnp.where(dev["valid"], offs[dev["rows"]], 0.0).astype(self._dtype)
            # one span + histogram sample per bucket solve, device-accurate
            # (block inside the span — the host-paced loop is per-phase
            # dispatch anyway; the fused sweep is where pipelining lives)
            with obs_span("solve.bucket", coordinate=self.coordinate_id,
                          bucket=bi, lanes=b.num_lanes,
                          soa=self._use_soa) as sp:
                t0 = _time.perf_counter()
                # photonwatch attribution: host (vsolve dispatch) vs
                # device (the block) split, stamped into the span's attrs
                # and the xla_*_seconds{site=} families
                with obs_attribute("solve.bucket", sp):
                    res = self._vsolve(w0, dev["x"], dev["y"], off_b,
                                       dev["w"], lane_regs[bi],
                                       *self._solve_extras(bi))
                    jax.block_until_ready(res.w)
                get_registry().observe(
                    "solve_bucket_seconds", _time.perf_counter() - t0,
                    coordinate=self.coordinate_id,
                    soa=str(self._use_soa).lower())
            coeffs.append(self._lanes_to_original(res.w, bi))
            results.append(res)
            if variances is not None:
                # per-entity variances, vmapped over the bucket's lanes
                # (reference computes them per SingleNodeOptimizationProblem),
                # at the TRANSFORMED-space iterates, then mapped through the
                # same coefficient transform as the means (createModel:89-95)
                v = self._vvar(res.w, dev["x"], dev["y"],
                               off_b, dev["w"], lane_regs[bi])
                if self._compact_variances:
                    v = self._expand_compact_variances(v, bi, lane_regs[bi])
                variances.append(self._lanes_to_original(v, bi))

        if self._proj is not None:
            coeffs = self._proj.back_project([np.asarray(c) for c in coeffs],
                                             fill=self._box_fill)
        w_stack, slot_of = stacked_coefficients(coeffs, self.buckets)
        var_stack = None
        if variances is not None:
            var_stack, _ = stacked_coefficients(variances, self.buckets)
            var_stack = np.asarray(var_stack)
        model = RandomEffectModel(
            w_stack=np.asarray(w_stack), slot_of=slot_of,
            random_effect_type=self.config.random_effect_type,
            feature_shard=self.config.feature_shard, task=self.task,
            variances=var_stack,
        )
        return self.merge_carry_through(model, init), results

    def merge_carry_through(self, model: RandomEffectModel,
                            init: Optional[RandomEffectModel]
                            ) -> RandomEffectModel:
        """Prior-model entities this update did not retrain (no active data —
        e.g. dropped by the existing-model-aware lower bound, or simply
        absent from this dataset) keep their old coefficients in the
        published model: the reference's leftOuterJoin passthrough
        (RandomEffectCoordinate.scala:114-127)."""
        if init is None:
            return model
        init = self._dense_init(init)
        carried = sorted(eid for eid in init.slot_of
                         if eid not in model.slot_of)
        if not carried:
            return model
        import dataclasses

        # the pipeline's dtype stays authoritative: a float64 avro prior
        # must not upcast a float32 model just because an entity carried
        out_dtype = np.asarray(model.w_stack).dtype
        rows = np.stack([init.w_stack[init.slot_of[eid]]
                         for eid in carried]).astype(out_dtype)
        slot_of = dict(model.slot_of)
        base = len(slot_of)
        for i, eid in enumerate(carried):
            slot_of[eid] = base + i
        w_stack = np.concatenate([np.asarray(model.w_stack), rows])
        var_stack = model.variances
        if var_stack is not None:
            # carried rows keep the prior model's variances when it has
            # them; a variance-less prior contributes zeros (its uncertainty
            # was never computed — 0 is the explicit "not estimated" marker
            # model_io uses for absent variances)
            if init.variances is not None:
                vrows = np.stack([init.variances[init.slot_of[eid]]
                                  for eid in carried]).astype(out_dtype)
            else:
                vrows = np.zeros_like(rows)
            var_stack = np.concatenate(
                [np.asarray(var_stack, vrows.dtype), vrows])
        return dataclasses.replace(model, w_stack=w_stack, slot_of=slot_of,
                                   variances=var_stack)

    def carry_through_scores(self, init: Optional[RandomEffectModel]
                             ) -> Optional[np.ndarray]:
        from photon_ml_tpu.parallel.bucketing import score_samples_sparse

        if init is None:
            return None
        init = self._dense_init(init)
        carried = np.fromiter(
            (eid for eid in init.slot_of if eid not in self._slot_of),
            np.int64)
        if carried.size == 0:
            return None
        slots = _slots_from(init.slot_of, self._entity_ids)
        slots = np.where(np.isin(self._entity_ids, carried),
                         slots, -1).astype(np.int32)
        w = jnp.asarray(np.asarray(init.w_stack, self._dtype))
        if self._sparse:
            s = score_samples_sparse(w, jnp.asarray(slots),
                                     self._x_idx_dev, self._x_val_dev)
        else:
            s = self._score_dense_full(w, jnp.asarray(slots))
        return np.asarray(s)[: self._n]

    def score(self, model: RandomEffectModel) -> np.ndarray:
        from photon_ml_tpu.parallel.bucketing import score_samples_sparse

        w = jnp.asarray(np.asarray(model.w_stack, self._dtype))
        if model.slot_of == self._slot_of:
            slots = self._sample_slots
        else:
            # model trained elsewhere: remap the RAW entity ids through its
            # slot map (an entity may be absent from our training buckets yet
            # present in the model)
            slots = jnp.asarray(_slots_from(model.slot_of, self._entity_ids))
        if self._sparse:
            return np.asarray(score_samples_sparse(
                w, slots, self._x_idx_dev, self._x_val_dev))[: self._n]
        return np.asarray(self._score_dense_full(w, slots))[: self._n]

    def _score_dense_full(self, w_stack: Array, slots: Array,
                          x_full: Optional[Array] = None) -> Array:
        """Full-sample dense scoring in whichever layout ``_x_full`` uses:
        [n, d], or [d, n] for narrow shards (bucketing.score_samples_t)."""
        from photon_ml_tpu.parallel.bucketing import (score_samples,
                                                      score_samples_t)

        x = self._x_full if x_full is None else x_full
        if self._x_full_is_t:
            return score_samples_t(w_stack, slots, x)
        return score_samples(w_stack, slots, x)

    # --- traceable-step interface (game/fused.py) ---
    # State = tuple of per-bucket lane coefficient arrays [(lanes, d), ...].

    def init_sweep_state(self, init: Optional[RandomEffectModel] = None) -> Tuple[Array, ...]:
        init = self._dense_init(init)
        lanes = []
        for bi, b in enumerate(self.buckets.buckets):
            if init is not None:
                lanes.append(self._put_entity(self._warm_start(bi, init)))
            else:
                # cold lanes in the SOLVE space (projected dim per bucket)
                solve_dim = self._dev[bi]["x"].shape[2]
                lanes.append(self._put_entity(
                    np.zeros((b.num_lanes, solve_dim), self._dtype)))
        return tuple(lanes)

    def sweep_data(self):
        """Bucket design matrices, full-sample scoring arrays and (when
        projecting) back-projection arrays, passed into the fused program as
        arguments (see Coordinate.sweep_data)."""
        d = dict(dev=self._dev, slots=self._sample_slots,
                 proj=self._proj_dev if self._proj is not None else None,
                 norm_fac=self._norm_fac_dev,
                 norm_shift=self._norm_shift_dev, norm_ii=self._norm_ii_dev,
                 box=self._box_lanes,
                 box_fill=None if self._box_fill is None
                 else jnp.asarray(self._box_fill))
        if self._sparse:
            d.update(x_idx=self._x_idx_dev, x_val=self._x_val_dev)
        else:
            d["x_full"] = self._x_full
        return d

    def trace_update(self, state: Tuple[Array, ...], offsets: Array,
                     reg: Optional[Regularization] = None,
                     key=None, data=None) -> Tuple[Tuple[Array, ...], Array]:
        # ``key`` unused: random effects have no per-update stochastic work
        # (down-sampling is a fixed-effect-only config, as in the reference).
        from photon_ml_tpu.parallel.bucketing import score_samples_sparse

        if data is None:
            data = self.sweep_data()
        reg = self.config.reg if reg is None else reg
        lane_regs = self._lane_regs(reg)
        offsets = offsets.astype(self._dtype)
        new_lanes = []
        for bi, (lanes, dev) in enumerate(zip(state, data["dev"])):
            off_b = jnp.where(dev["valid"], offsets[dev["rows"]], 0.0)
            res = self._vsolve(lanes, dev["x"], dev["y"], off_b, dev["w"],
                               lane_regs[bi], *self._solve_extras(bi, data))
            new_lanes.append(res.w)
        w_stack = self.trace_publish(tuple(new_lanes), data=data)
        if self._sparse:
            score = score_samples_sparse(
                w_stack, data["slots"], data["x_idx"], data["x_val"])[: self._n]
        else:
            score = self._score_dense_full(w_stack, data["slots"],
                                           data["x_full"])[: self._n]
        return tuple(new_lanes), score

    def trace_publish(self, state: Tuple[Array, ...], data=None) -> Array:
        from photon_ml_tpu.parallel.bucketing import stack_bucket_lanes

        if self._norm is not None:
            # original-space lanes BEFORE back-projection/stacking (per-lane
            # context maps live in the compact solve space)
            if data is None:
                data = self.sweep_data()
            state = tuple(self._lanes_to_original(lanes, bi, data=data)
                          for bi, lanes in enumerate(state))
        if self._proj is not None:
            # traced twin of ProjectedBuckets.back_project (margin-exact):
            # lanes return to full dim before stacking.  Projection arrays
            # come through ``data`` so they enter the compiled program as
            # arguments (sweep_data convention), not baked constants.
            if data is None:
                data = self.sweep_data()
            proj = data["proj"]
            state = tuple(self._traced_back_project(bi, proj[bi], lanes,
                                                    fill=data.get("box_fill"))
                          for bi, lanes in enumerate(state))
        return stack_bucket_lanes(state, self._slot_idx_dev,
                                  len(self._sorted_ids))

    def _traced_back_project(self, bi: int, arr: Array, lanes: Array,
                             fill: Optional[Array] = None) -> Array:
        kind = self._proj_kinds[bi]
        if kind == "random":
            return lanes @ arr.T  # shared Gaussian (ProjectionMatrix.scala:127)
        e = lanes.shape[0]
        if fill is not None:
            # box-constrained compact solve: unobserved features publish
            # clip(0, lo, hi) (BucketProjection.back_project's fill
            # semantics); padded slots route out of range and drop so the
            # 'set' scatter can never clobber a genuinely observed column
            safe = jnp.where(arr < 0, self.dim, arr)
            out = jnp.broadcast_to(fill.astype(lanes.dtype), (e, self.dim))
            out = out.at[jnp.arange(e)[:, None], safe].set(lanes, mode="drop")
            # padding lanes (index row entirely -1) stay zero, matching
            # BucketProjection.back_project — no fill rows for nonexistent
            # entities (today both stacking paths drop them anyway)
            return jnp.where((arr >= 0).any(axis=1)[:, None], out, 0.0)
        # index compaction: scatter each lane's projected slots into full dim;
        # padded slots (idx<0) carry value 0, so colliding on column 0 is inert
        safe = jnp.where(arr < 0, 0, arr)
        vals = jnp.where(arr >= 0, lanes, 0.0)
        out = jnp.zeros((e, self.dim), lanes.dtype)
        return out.at[jnp.arange(e)[:, None], safe].add(vals)

    def export_model(self, published: np.ndarray) -> RandomEffectModel:
        return RandomEffectModel(
            w_stack=np.asarray(published), slot_of=dict(self._slot_of),
            random_effect_type=self.config.random_effect_type,
            feature_shard=self.config.feature_shard, task=self.task)

    def init_sweep_variances(self) -> "Array | Tuple[Array, ...]":
        if self.config.variance == VarianceComputationType.NONE:
            return jnp.zeros(0, self._dtype)
        return tuple(jnp.zeros((b.num_lanes, self.dim), self._dtype)
                     for b in self.buckets.buckets)

    def trace_variances(self, state: Tuple[Array, ...], offsets: Array,
                        reg: Optional[Regularization] = None,
                        key=None, data=None) -> Tuple[Array, ...]:
        """Traced per-entity variances at this update's lane iterates and
        traced ``reg``, vmapped per bucket exactly as the host path's
        update() does."""
        dev_buckets = self._dev if data is None else data["dev"]
        offs = offsets.astype(self._dtype)
        lane_regs = self._lane_regs(self.config.reg if reg is None else reg)
        out = []
        for bi, (lanes, dev) in enumerate(zip(state, dev_buckets)):
            off_b = jnp.where(dev["valid"], offs[dev["rows"]], 0.0)
            v = self._vvar(lanes, dev["x"], dev["y"], off_b,
                           dev["w"], lane_regs[bi])
            if self._compact_variances:
                v = self._expand_compact_variances(v, bi, lane_regs[bi])
            out.append(self._lanes_to_original(v, bi))
        return tuple(out)

    def export_variances(self, v) -> np.ndarray:
        var_stack, _ = stacked_coefficients([np.asarray(b) for b in v],
                                            self.buckets)
        return np.asarray(var_stack)

    # --- external (validation) scoring (fused validated sweeps) ---------

    def external_data(self, data: GameData):
        """Held-out slots + design for this coordinate, device-resident
        once.  Slots map the external entity ids through THIS RUN's trained
        slot order (the stacked layout ``trace_publish`` emits); entities
        this run never trained get -1 and score 0 — carried warm-start
        entities are a host-side CONSTANT (``carry_through_scores_on``)."""
        from photon_ml_tpu.utils.transfer import chunked_device_put

        shard = data.features[self.config.feature_shard]
        ids = np.asarray(data.id_tags[self.config.random_effect_type],
                         np.int64)
        out = {"slots": jnp.asarray(_slots_from(self._slot_of, ids))}
        if isinstance(shard, SparseShard):
            out["x_idx"] = chunked_device_put(shard.indices, np.int32)
            out["x_val"] = chunked_device_put(shard.values, self._dtype)
        else:
            out["x"] = chunked_device_put(np.asarray(shard), self._dtype)
        return out

    def trace_score_external(self, published: Array, vdata) -> Array:
        """== RandomEffectModel.score on the published stack: gather + row
        dot (dense) or the two-level sparse gather."""
        from photon_ml_tpu.parallel.bucketing import (score_samples,
                                                      score_samples_sparse)

        if "x" in vdata:
            return score_samples(published, vdata["slots"], vdata["x"])
        return score_samples_sparse(published, vdata["slots"],
                                    vdata["x_idx"], vdata["x_val"])

    def carry_through_scores_on(self, init: Optional[RandomEffectModel],
                                data: GameData) -> Optional[np.ndarray]:
        """Carried (never-retrained) entities' contribution on an EXTERNAL
        sample set — ``carry_through_scores``' exact semantics evaluated on
        ``data`` instead of the training samples."""
        from photon_ml_tpu.parallel.bucketing import (score_samples,
                                                      score_samples_sparse)

        if init is None:
            return None
        init = self._dense_init(init)
        carried = np.fromiter(
            (eid for eid in init.slot_of if eid not in self._slot_of),
            np.int64)
        if carried.size == 0:
            return None
        ids = np.asarray(data.id_tags[self.config.random_effect_type],
                         np.int64)
        slots = _slots_from(init.slot_of, ids)
        slots = np.where(np.isin(ids, carried), slots, -1).astype(np.int32)
        w = jnp.asarray(np.asarray(init.w_stack, self._dtype))
        shard = data.features[self.config.feature_shard]
        if isinstance(shard, SparseShard):
            s = score_samples_sparse(
                w, jnp.asarray(slots),
                jnp.asarray(np.asarray(shard.indices, np.int32)),
                jnp.asarray(np.asarray(shard.values, self._dtype)))
        else:
            s = score_samples(w, jnp.asarray(slots),
                              jnp.asarray(np.asarray(shard, self._dtype)))
        return np.asarray(s)

    def tracker_summary(self, trackers) -> dict:
        """Per-entity solve statistics, padded lanes excluded (reference
        RandomEffectOptimizationTracker.scala:158 summary over thousands of
        entity solves)."""
        from photon_ml_tpu.opt.types import summarize_solver_results

        masks = [np.asarray(b.entity_lanes) >= 0 for b in self.buckets.buckets]
        return summarize_solver_results(list(trackers), valid_masks=masks)


def build_coordinate(coordinate_id: str, data: GameData, config: CoordinateConfig,
                     task: TaskType, mesh: Optional[Mesh] = None,
                     norm: Optional[NormalizationContext] = None,
                     seed: int = 0, dtype=np.float32,
                     existing_model_keys: Optional[frozenset] = None) -> Coordinate:
    """Reference CoordinateFactory.build (CoordinateFactory.scala:34-113).

    ``dtype``: compute precision for this coordinate's device arrays; the
    reference computes in JVM float64 throughout — pass ``np.float64`` for
    reference-precision parity, keep the float32 default for TPU throughput.
    ``existing_model_keys``: warm-start entity ids for the random-effect
    lower bound's existing-model semantics (see bucketing._group_rows).
    """
    if np.dtype(dtype).itemsize == 8 and not jax.config.jax_enable_x64:
        raise ValueError(
            f"dtype {np.dtype(dtype).name} requires jax_enable_x64: without it "
            "jax silently truncates every array to 32 bits and the solve would "
            'NOT run at the requested precision — jax.config.update('
            '"jax_enable_x64", True) first (CPU; TPU hardware is 32-bit)')
    if isinstance(config, FixedEffectConfig):
        return FixedEffectCoordinate(coordinate_id, data, config, task, mesh, norm,
                                     dtype=dtype)
    if isinstance(config, RandomEffectConfig):
        return RandomEffectCoordinate(coordinate_id, data, config, task, mesh, seed,
                                      dtype=dtype, norm=norm,
                                      existing_model_keys=existing_model_keys)
    raise TypeError(f"unknown coordinate config {type(config)!r}")
