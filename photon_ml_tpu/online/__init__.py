"""photonlearn — the online-learning loop (ROADMAP item 3).

Three pieces turn training and serving into one system:

- ``delta_log``: durable append-only log of coefficient-row deltas keyed by
  the swapper's ``(generation, delta_version)`` identity — segment files
  per generation, CRC-framed records, torn-tail-tolerant replay,
  compaction at swap boundaries.
- ``trainer``: ``IncrementalTrainer`` — warm-started per-entity
  random-effect refits from fresh mini-batches, solved with the batched
  SoA Newton path, published as ordered deltas to the live store AND the
  log.
- ``catchup``: idempotent replay — a rotated-in generation or a second
  replica store follows the log to convergence.
"""

from photon_ml_tpu.online.catchup import (CatchupStats, LogFollower,
                                          replay_into_store)
from photon_ml_tpu.online.delta_log import DeltaLog, DeltaRecord
from photon_ml_tpu.online.trainer import (Example, IncrementalTrainer,
                                          RefitReport, TrainerConfig,
                                          example_from_json)

__all__ = [
    "CatchupStats", "DeltaLog", "DeltaRecord", "Example",
    "IncrementalTrainer", "LogFollower", "RefitReport", "TrainerConfig",
    "example_from_json", "replay_into_store",
]
