"""Model-dir tarstream packing for photonrepl's snapshot bootstrap RPC.

A replica with no usable state (fresh spool, or compaction passed its
identity) cannot be caught up by log replay — it needs the owner's BASE:
the model directory the serving store was built from.  The snapshot RPC
ships that directory as one uncompressed tar with a whole-stream CRC32 in
the framed header, and the replica rebuilds its engine from the extracted
copy exactly as if it had been pointed at the directory locally
(``storage/model_io.load_model_bundle`` resolves both the flat and
``best/``-nested layouts, so the tar simply preserves the tree).

Packing is DETERMINISTIC — sorted member order, zeroed timestamps and
ownership — so two snapshots of an unchanged directory are byte-identical
and the CRC is a meaningful identity, not an mtime lottery.

Unpacking is DEFENSIVE: only regular files and directories, no absolute
paths, no ``..`` traversal, no links — the tar comes over a network socket
and must not be able to write outside its destination.
"""

from __future__ import annotations

import io
import os
import tarfile
import zlib
from typing import Tuple


class SnapshotError(ValueError):
    """A snapshot stream was rejected (checksum, framing, or a member that
    tried to escape the destination directory)."""


def pack_model_dir(model_dir: str) -> Tuple[bytes, int]:
    """Tar ``model_dir`` (deterministically) -> ``(data, crc32)``."""
    if not os.path.isdir(model_dir):
        raise SnapshotError(f"snapshot source is not a directory: "
                            f"{model_dir!r}")
    members = []
    for root, dirs, files in os.walk(model_dir):
        dirs.sort()
        rel_root = os.path.relpath(root, model_dir)
        if rel_root != ".":
            members.append((rel_root, None))
        for name in sorted(files):
            rel = os.path.join(rel_root, name) if rel_root != "." else name
            members.append((rel, os.path.join(root, name)))
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w", format=tarfile.PAX_FORMAT) as tf:
        for rel, path in sorted(members):
            if path is None:
                info = tarfile.TarInfo(rel)
                info.type = tarfile.DIRTYPE
                info.mode = 0o755
                tf.addfile(info)
                continue
            info = tarfile.TarInfo(rel)
            info.size = os.path.getsize(path)
            info.mode = 0o644
            info.mtime = 0
            info.uid = info.gid = 0
            info.uname = info.gname = ""
            with open(path, "rb") as f:
                tf.addfile(info, f)
    data = buf.getvalue()
    return data, zlib.crc32(data)


def unpack_snapshot(data: bytes, crc32: int, dest_dir: str) -> None:
    """Verify the stream CRC and extract into ``dest_dir`` (created fresh).
    Raises :class:`SnapshotError` on checksum mismatch or any member that
    is not a plain file/directory safely inside the destination."""
    if zlib.crc32(data) != crc32:
        raise SnapshotError("snapshot stream failed its CRC32 check")
    os.makedirs(dest_dir, exist_ok=True)
    dest_real = os.path.realpath(dest_dir)
    try:
        with tarfile.open(fileobj=io.BytesIO(data), mode="r:") as tf:
            for info in tf:
                target = os.path.realpath(os.path.join(dest_dir, info.name))
                if target != dest_real and not target.startswith(
                        dest_real + os.sep):
                    raise SnapshotError(
                        f"snapshot member escapes destination: {info.name!r}")
                if info.isdir():
                    os.makedirs(target, exist_ok=True)
                elif info.isreg():
                    os.makedirs(os.path.dirname(target), exist_ok=True)
                    src = tf.extractfile(info)
                    assert src is not None  # isreg() members are readable
                    with open(target, "wb") as out:
                        out.write(src.read())
                else:
                    raise SnapshotError(
                        f"snapshot member has forbidden type: {info.name!r}")
    except tarfile.TarError as e:
        raise SnapshotError(f"unreadable snapshot tar: {e}") from e
