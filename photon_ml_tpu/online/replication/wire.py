"""photonrepl wire schema: bounded newline-JSON control lines, plus one
binary interlude for the snapshot tarstream.

Both ends frame control traffic exactly like the serving front end
(``serving/frontend/protocol.py``): one JSON object per line under a hard
byte bound, so a malformed or malicious peer cannot grow either side's
receive buffer without limit.  Record lines carry the delta-log payload
TEXT verbatim with the SAME CRC32 the on-disk frame carries
(``online/delta_log.py``) — a record that survives the wire check is
bit-identical to the owner's durable frame, and appending it to the
replica's mirror log re-creates the owner's bytes.

Lines, client -> server::

    {"cmd": "subscribe", "last": [gen, ver] | null, "token": "..."?}
    {"cmd": "ack", "last": [gen, ver]}

Lines, server -> client::

    {"error": "..."}                               # one frame, then close
    {"repl": "resume", "mode": "log" | "snapshot",
     "generation": G, "floor": F,
     "who": "...", "t0": ..., "t1": ..., "t2": ...}  # first reply; the
                                                   # who/t0/t1/t2 fields
                                                   # close the photonpulse
                                                   # clock ping-pong when
                                                   # the hello carried t0
    {"repl": "snapshot", "bytes": N, "crc32": C,
     "generation": G, "version": "..."}            # then N raw tar bytes
    {"repl": "delta", "crc": C, "p": "<payload>"}  # one log record
    {"repl": "restart", "reason": "..."}           # re-subscribe from scratch

``floor`` is the owner's base generation — the generation at which the
currently-serving model directory was activated.  Every streamed record
has ``generation >= floor``; anything older is baked into (or superseded
by) the snapshot the client holds.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Optional, Tuple

from photon_ml_tpu.online.delta_log import DeltaRecord

_LEN_CRC = struct.Struct("<II")  # delta_log frame header: payload len, crc


class WireError(ValueError):
    """A peer sent a frame that violates the schema or its checksum."""


def encode_record_line(record: DeltaRecord,
                       tp: Optional[str] = None) -> bytes:
    """One ``{"repl": "delta"}`` line.  The payload text and CRC are lifted
    from ``DeltaRecord.encode()`` so they are bit-identical to the owner's
    on-disk frame — no second serialization that could round differently.

    ``tp``: optional photonpulse trace context (``obs.pulse.to_wire``
    form).  It rides BESIDE the payload, never inside it — the payload/CRC
    bit-parity with the on-disk frame is the replication invariant and
    tracing must not perturb it.  Receivers treat a missing or malformed
    ``tp`` as untraced."""
    frame = record.encode()
    _, crc = _LEN_CRC.unpack_from(frame)
    payload = frame[_LEN_CRC.size:].decode("utf-8")
    obj = {"repl": "delta", "crc": crc, "p": payload}
    if tp is not None:
        obj["tp"] = tp
    return (json.dumps(obj, separators=(",", ":")) + "\n").encode("utf-8")


def decode_record_obj(obj: dict) -> DeltaRecord:
    """Parse + CRC-verify a ``{"repl": "delta"}`` object.  Raises
    :class:`WireError` on any mismatch — a corrupt record must never reach
    the mirror log."""
    try:
        payload = str(obj["p"]).encode("utf-8")
        crc = int(obj["crc"])
    except (KeyError, TypeError, ValueError) as e:
        raise WireError(f"malformed delta frame: {e}") from e
    if zlib.crc32(payload) != crc:
        raise WireError("delta frame failed its CRC32 check")
    try:
        return DeltaRecord.decode_payload(payload)
    except (ValueError, KeyError, TypeError) as e:
        raise WireError(f"undecodable delta payload: {e}") from e


def parse_identity(value) -> Optional[Tuple[int, int]]:
    """``[gen, ver]`` -> tuple, ``None`` passed through.  Raises
    :class:`WireError` on anything else."""
    if value is None:
        return None
    try:
        gen, ver = value
        return (int(gen), int(ver))
    except (TypeError, ValueError) as e:
        raise WireError(f"malformed identity {value!r}") from e


def parse_line(line: bytes) -> dict:
    """One wire line -> dict, schema errors typed."""
    try:
        obj = json.loads(line.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"undecodable wire line: {e}") from e
    if not isinstance(obj, dict):
        raise WireError("wire line is not a JSON object")
    return obj
