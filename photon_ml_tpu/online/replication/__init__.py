"""photonrepl — the network replication plane for photonlearn.

The delta log (online/delta_log.py) made coefficient updates durable and
ordered; catch-up (online/catchup.py) made them replayable; but PR 9 left
replicas tailing the owner's log through a SHARED DIRECTORY, with no
bootstrap path for a brand-new replica and an owner that compacts with no
regard for slow followers.  This package closes all three gaps:

  - :mod:`server` — asyncio TCP log server on the delta-log owner.  Streams
    CRC-carried records to subscribers, serves checksummed model-dir
    tarstream snapshots for bootstrap, pins the owner's compaction floor at
    the minimum acknowledged follower identity (with byte/age caps so a
    dead follower cannot pin the log forever), and bounds per-follower
    send queues with log catch-up on overflow.
  - :mod:`client` — replica-side subscriber.  Bootstraps from a snapshot
    RPC when it has no usable state, then mirrors the live record stream
    into a LOCAL delta log, so every existing consumer — ``LogFollower``
    tailing, ``HotSwapper`` replay-before-activate — works on the mirror
    unchanged.  ``serve.py --subscribe host:port`` replaces the
    shared-directory requirement end to end.
  - :mod:`snapshot` — deterministic model-dir tar packing/unpacking with a
    whole-stream CRC32.
  - :mod:`wire` — the framed line schema shared by both ends (bounded
    newline JSON via ``serving/frontend/protocol.py``, record payloads
    bit-identical to the on-disk log frames).
"""

from photon_ml_tpu.online.replication.client import (ReplicationClient,
                                                     ReplicationClientConfig)
from photon_ml_tpu.online.replication.server import (ReplicationConfig,
                                                     ReplicationServer,
                                                     ThreadedReplicationServer,
                                                     attach_replication)

__all__ = [
    "ReplicationClient",
    "ReplicationClientConfig",
    "ReplicationConfig",
    "ReplicationServer",
    "ThreadedReplicationServer",
    "attach_replication",
]
