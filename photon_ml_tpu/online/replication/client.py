"""photonrepl replica client: subscribe, bootstrap, mirror.

The client's one structural idea: it does NOT apply records to a store.
It mirrors the owner's record stream into a LOCAL delta log (the
"mirror", a plain ``online/delta_log.DeltaLog`` in the spool directory),
so every existing consumer works on the mirror unchanged — the serving
process attaches it exactly like a shared-directory ``--delta-log``:
``LogFollower`` tails it live, and ``HotSwapper`` replays it before
activating any hot-swapped generation.  The wire CRC is checked before a
record touches the mirror, and the mirror frame is bit-identical to the
owner's durable frame.

Spool layout (``spool_dir``)::

    log/                 the mirror delta log
    base-<gen>-<n>/      extracted snapshot model dirs (latest two kept)
    state.json           {"floor": G, "base": "<dir>"}

Lifecycle: connect -> subscribe (last applied identity + base floor +
optional auth token) -> the server replies ``mode=log`` (live records
follow immediately) or ``mode=snapshot`` (a checksummed model-dir
tarstream precedes them).  Snapshot frames can ALSO arrive mid-stream —
that is the owner hot-swapping; the client extracts the new base and
invokes ``on_snapshot(model_dir, generation)`` so the serving process
hot-swaps with replay-before-activate off the mirror.  On any error or a
``{"repl": "restart"}`` frame the client reconnects with exponential
backoff and re-subscribes from its mirror identity — the server decides
log replay vs snapshot from there.

Acks flow upstream every ``ack_every`` records (or ``ack_interval_s`` of
idle): they are what the owner's retention floor and lag gauges key on.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import logging
import os
import shutil
import threading
import time
from typing import Callable, Optional, Tuple

from photon_ml_tpu.chaos.injector import fault as _chaos_fault
from photon_ml_tpu.obs.pulse import clock as pulse_clock
from photon_ml_tpu.obs.pulse.context import bind as ctx_bind
from photon_ml_tpu.obs.pulse.context import from_wire as ctx_from_wire
from photon_ml_tpu.obs.pulse.context import note_delta as ctx_note_delta
from photon_ml_tpu.obs.trace import enabled as obs_enabled
from photon_ml_tpu.obs.trace import instant as obs_instant
from photon_ml_tpu.online.delta_log import DeltaLog
from photon_ml_tpu.online.replication.snapshot import (SnapshotError,
                                                       unpack_snapshot)
from photon_ml_tpu.online.replication.wire import (WireError,
                                                   decode_record_obj,
                                                   parse_identity, parse_line)
from photon_ml_tpu.serving.frontend.protocol import (DEFAULT_MAX_LINE_BYTES,
                                                     BoundedLineReader,
                                                     LineTooLong, encode)

logger = logging.getLogger("photon_ml_tpu.online.replication")

_MAX_SNAPSHOT_BYTES = 4 << 30  # refuse a header promising more than this


@dataclasses.dataclass(frozen=True)
class ReplicationClientConfig:
    host: str
    port: int
    spool_dir: str
    auth_token: Optional[str] = None
    max_line_bytes: int = DEFAULT_MAX_LINE_BYTES
    connect_timeout_s: float = 10.0
    ack_every: int = 64
    ack_interval_s: float = 1.0
    backoff_initial_s: float = 0.2
    backoff_max_s: float = 5.0
    # mirror durability: "rotate" keeps warm-restart resume cheap without
    # paying an fsync per record on the replica's apply path
    mirror_fsync: str = "rotate"


class ReplicationClient:
    """Threaded subscriber feeding one spool directory (module docstring).

    ``on_snapshot(model_dir, generation)`` runs on the client thread after
    a snapshot is extracted and the spool state updated; the serving
    process wires it to ``HotSwapper.swap`` (``cli/serve.py
    --subscribe``).  It is NOT called for the snapshot consumed by
    ``bootstrap()`` — the caller builds its first engine from
    ``model_dir`` directly.
    """

    def __init__(self, config: ReplicationClientConfig,
                 on_snapshot: Optional[Callable[[str, int], None]] = None,
                 registry=None):
        self.config = config
        self.on_snapshot = on_snapshot
        self._registry = registry
        os.makedirs(config.spool_dir, exist_ok=True)
        self.mirror_path = os.path.join(config.spool_dir, "log")
        self._state_path = os.path.join(config.spool_dir, "state.json")
        self.floor: Optional[int] = None
        self.model_dir: Optional[str] = None
        self._load_state()
        self._mirror = DeltaLog(self.mirror_path,
                                fsync=config.mirror_fsync)
        if self.floor is not None:
            # warm spool: mirror records below the base's floor describe a
            # superseded lineage (the owner swapped mid-stream in a past
            # life) — drop them so a replay of the mirror never applies
            # them onto this or a newer base
            self._mirror.compact(self.floor)
        self._last = self._mirror.last_identity()
        self._bootstrapped = threading.Event()
        if self.model_dir is not None:
            self._bootstrapped.set()  # warm spool: base already on disk
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="photonrepl-client")
        self._snapshot_seq = 0
        self.last_resume_mode: Optional[str] = None
        self.records_applied = 0
        self.snapshots_received = 0
        self.reconnects = 0
        self._error: Optional[BaseException] = None

    # -- state file --------------------------------------------------------
    def _load_state(self) -> None:
        try:
            with open(self._state_path, "r", encoding="utf-8") as f:
                state = json.load(f)
            floor = state.get("floor")
            base = state.get("base")
            if isinstance(floor, int) and isinstance(base, str) and \
                    os.path.isdir(base):
                self.floor = floor
                self.model_dir = base
        except (OSError, json.JSONDecodeError):
            pass  # cold spool

    def _save_state(self) -> None:
        tmp = self._state_path + ".tmp"
        # photonlint: disable=blocking-in-async -- ~100-byte atomic
        # state-file write on the spool volume; an executor hop costs more
        # than the write, and the floor/base pair must be durable before
        # the snapshot is acted on
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"floor": self.floor, "base": self.model_dir}, f)
        os.replace(tmp, self._state_path)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ReplicationClient":
        self._thread.start()
        return self

    def bootstrap(self, timeout: float = 60.0) -> str:
        """Block until a base model directory is available (warm spool, or
        the first snapshot landed) and return it."""
        if not self._bootstrapped.wait(timeout):
            raise RuntimeError(
                f"replication bootstrap did not complete within {timeout}s"
                + (f": {self._error}" if self._error else ""))
        assert self.model_dir is not None
        return self.model_dir

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._thread.join(timeout)

    @property
    def last_identity(self) -> Optional[Tuple[int, int]]:
        return self._last

    @property
    def worker_thread(self) -> threading.Thread:
        """The subscriber loop thread — what a chaos.health.Watchdog
        registers."""
        return self._thread

    def _run(self) -> None:
        try:
            asyncio.run(self._main())
        except BaseException as e:  # pragma: no cover - defensive
            self._error = e
            logger.exception("photonrepl client died")

    async def _main(self) -> None:
        backoff = self.config.backoff_initial_s
        first = True
        while not self._stop.is_set():
            if not first:
                self.reconnects += 1
                if self._registry is not None:
                    self._registry.inc("repl_client_reconnects_total")
            first = False
            try:
                await self._session()
                backoff = self.config.backoff_initial_s
            except (ConnectionError, OSError, asyncio.TimeoutError,
                    EOFError, WireError, SnapshotError, LineTooLong) as e:
                self._error = e
                logger.warning("photonrepl client: session ended: %s", e)
            if self._stop.is_set():
                return
            deadline = time.monotonic() + backoff
            while not self._stop.is_set() and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            backoff = min(backoff * 2, self.config.backoff_max_s)

    # -- one connection ----------------------------------------------------
    async def _session(self) -> None:
        cfg = self.config
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(cfg.host, cfg.port),
            cfg.connect_timeout_s)
        try:
            br = BoundedLineReader(reader.read, cfg.max_line_bytes)
            hello = {"cmd": "subscribe",
                     "last": list(self._last) if self._last else None,
                     "floor": self.floor,
                     # photonpulse clock ping-pong: the server echoes t0
                     # and adds its own t1/t2 stamps to the resume reply
                     "t0": pulse_clock.now_ns()}
            if cfg.auth_token is not None:
                hello["token"] = cfg.auth_token
            writer.write(encode(hello))
            await writer.drain()
            line = await asyncio.wait_for(br.readline(),
                                          cfg.connect_timeout_s)
            t3 = pulse_clock.now_ns()
            if line is None:
                raise ConnectionError("server closed during subscribe")
            obj = parse_line(line)
            if "error" in obj:
                raise ConnectionError(f"subscribe refused: {obj['error']}")
            if obj.get("repl") != "resume":
                raise WireError(f"expected resume, got {obj!r}")
            mode = obj.get("mode")
            self.last_resume_mode = mode
            if self._registry is not None:
                self._registry.inc("repl_client_resume_total", mode=mode)
            obs_instant("repl.client.resume", mode=mode)
            self._note_clock(obj, t3)
            if mode == "snapshot" and self._last is not None:
                # our spool lineage is dead (owner swapped past us or we
                # diverged): the incoming stream restarts identity-fresh
                self._reset_mirror()
            await self._stream(f=br, writer=writer)
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001 — best-effort close
                pass

    def _note_clock(self, resume: dict, t3: int) -> None:
        """Fold the resume reply's clock stamps into the offset table.
        Tolerant: any missing or non-integer field means the owner did not
        (or could not) answer the ping-pong — skip, never fail the
        subscribe over telemetry."""
        t0, t1, t2 = (resume.get("t0"), resume.get("t1"), resume.get("t2"))
        who = resume.get("who")
        if not (isinstance(who, str) and who and
                all(isinstance(t, int) for t in (t0, t1, t2))):
            return
        offset, rtt = pulse_clock.observe_exchange(who, t0, t1, t2, t3)
        obs_instant("repl.client.clock", peer=who,
                    offset_ns=offset, rtt_ns=rtt)

    async def _stream(self, f: BoundedLineReader,
                      writer: asyncio.StreamWriter) -> None:
        unacked = 0
        last_ack = time.monotonic()

        async def _ack(force: bool = False) -> None:
            nonlocal unacked, last_ack
            now = time.monotonic()
            due = unacked >= self.config.ack_every or (
                unacked > 0 and now - last_ack >= self.config.ack_interval_s)
            if not (force or due):
                return
            if self._last is not None:
                writer.write(encode({"cmd": "ack",
                                     "last": list(self._last)}))
                await writer.drain()
            unacked = 0
            last_ack = now

        while not self._stop.is_set():
            act = _chaos_fault("repl.client.read")
            if act is not None:
                # client-side session death: _main's backoff reconnect is
                # the heal path — resume via log or snapshot fallback
                raise act.to_error()
            try:
                line = await asyncio.wait_for(
                    f.readline(), self.config.ack_interval_s)
            except asyncio.TimeoutError:
                await _ack()
                continue
            if line is None:
                await _ack(force=unacked > 0)
                raise ConnectionError("server closed the stream")
            if not line.strip():
                continue
            obj = parse_line(line)
            kind = obj.get("repl")
            if kind == "delta":
                rec = decode_record_obj(obj)
                if obs_enabled():
                    # tolerant: a torn or garbage "tp" degrades to an
                    # untraced record, never to a failed one
                    ctx = ctx_from_wire(obj.get("tp"))
                    if ctx is not None:
                        ctx_note_delta(rec.identity, ctx)
                        with ctx_bind(ctx):
                            obs_instant("repl.client.recv",
                                        generation=rec.generation,
                                        version=rec.delta_version)
                if self._last is None or rec.identity > self._last:
                    self._mirror.append(rec)
                    self._last = rec.identity
                    self.records_applied += 1
                    unacked += 1
                    if self._registry is not None:
                        self._registry.inc("repl_client_records_total")
                await _ack()
            elif kind == "snapshot":
                await self._take_snapshot(f, obj)
                await _ack(force=True)
            elif kind == "restart":
                reason = obj.get("reason")
                logger.info("photonrepl client: server asked for restart "
                            "(%s)", reason)
                raise ConnectionError(f"server restart: {reason}")
            elif "error" in obj:
                raise ConnectionError(f"server error: {obj['error']}")
            # unknown repl kinds are ignored: forward compatibility

    async def _take_snapshot(self, f: BoundedLineReader, obj: dict) -> None:
        try:
            nbytes = int(obj["bytes"])
            crc = int(obj["crc32"])
            gen = int(obj["generation"])
        except (KeyError, TypeError, ValueError) as e:
            raise WireError(f"malformed snapshot header: {e}") from e
        if not 0 <= nbytes <= _MAX_SNAPSHOT_BYTES:
            raise WireError(f"implausible snapshot size {nbytes}")
        data = await f.readexactly(nbytes)
        self._snapshot_seq += 1
        dest = os.path.join(self.config.spool_dir,
                            f"base-{gen:010d}-{self._snapshot_seq}")
        # CRC + tar extraction scale with snapshot size (up to
        # _MAX_SNAPSHOT_BYTES): off the loop, or the stream's heartbeats
        # stall for the whole unpack.  Raises SnapshotError on mismatch.
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, unpack_snapshot, data, crc, dest)
        prev_dir = self.model_dir
        first = not self._bootstrapped.is_set()
        self.model_dir = dest
        self.floor = gen
        self._save_state()
        # the new base supersedes every mirrored record below its
        # generation; compacting here keeps warm restarts clean too
        self._mirror.compact(gen)
        self.snapshots_received += 1
        if self._registry is not None:
            self._registry.inc("repl_client_snapshots_total")
        obs_instant("repl.client.snapshot", generation=gen, nbytes=nbytes)
        logger.info("photonrepl client: snapshot gen %d (%d bytes) -> %s",
                    gen, nbytes, dest)
        if first:
            self._bootstrapped.set()
        elif self.on_snapshot is not None:
            # mid-stream owner swap: hand the new base to the serving
            # process (HotSwapper replays the mirror before activating)
            self.on_snapshot(dest, gen)
        if prev_dir and prev_dir != dest and \
                os.path.dirname(os.path.abspath(prev_dir)) == \
                os.path.abspath(self.config.spool_dir):
            # deleting a whole model directory is as slow as unpacking one
            await loop.run_in_executor(
                None, lambda: shutil.rmtree(prev_dir, ignore_errors=True))

    def _reset_mirror(self) -> None:
        """The spool's lineage no longer matches the owner: wipe the
        mirror so the fresh stream starts on a clean identity chain."""
        self._mirror.close()
        for name in os.listdir(self.mirror_path):
            if name.startswith("segment-") and name.endswith(".log"):
                try:
                    os.remove(os.path.join(self.mirror_path, name))
                except OSError:
                    pass
        self._mirror = DeltaLog(self.mirror_path,
                                fsync=self.config.mirror_fsync)
        self._last = None
        logger.info("photonrepl client: mirror reset for a fresh lineage")
