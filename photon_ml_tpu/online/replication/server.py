"""photonrepl log server: the delta-log owner's replication endpoint.

One asyncio TCP server runs next to the log owner (``cli/learn.py
--repl-listen``, or in-process in tests/bench).  Each subscriber gets:

  - **Identity-based resume.**  The subscribe hello carries the client's
    last applied ``(generation, delta_version)`` and the base-generation
    ``floor`` it bootstrapped at.  When the floor matches the owner's and
    the retained log covers the identity, the server replays forward from
    the log (``repl_resume_total{mode="log"}``); otherwise the client gets
    a fresh snapshot bootstrap (``mode="snapshot"``): the owner's model
    directory as a checksummed tarstream, followed by every retained
    record of the current base lineage.
  - **Live tailing.**  A ``DeltaLog`` append listener fans each published
    record into per-follower BOUNDED queues.  A follower that cannot keep
    up overflows its queue and is switched to log catch-up — it re-reads
    the records it missed from the durable log, then rejoins the live
    stream.  Memory per follower is bounded by the queue, not by the
    slowest consumer.
  - **In-stream hot swap.**  When the owner activates a new generation
    (``HotSwapper`` calls :meth:`ReplicationServer.note_generation`), each
    follower's sender finishes draining the pre-swap records its current
    base can still use, then ships the NEW snapshot inline and continues
    with post-swap records — the replica hot-swaps with
    replay-before-activate off its mirror, never missing an update.
  - **Retention floor.**  The server installs a ``retention_pin`` on the
    owner's log: compaction keeps segments at or above the minimum
    generation a connected follower still needs (its last acknowledged
    identity).  Byte and age caps bound the pin — a follower that stops
    acking, or whose pinned segments exceed the byte budget, is EVICTED
    (one ``{"repl": "restart"}`` frame, connection closed) and falls back
    to snapshot bootstrap on reconnect, so one dead follower can never pin
    the log forever.

Auth: with ``ReplicationConfig.auth_token`` set, the subscribe hello must
carry the shared secret; the compare is constant-time and a failed hello
gets exactly one ``{"error": "unauthorized"}`` frame before the close.

Metrics (photonscope registry): ``repl_followers`` gauge,
``repl_follower_lag_records`` / ``repl_follower_lag_bytes`` per-peer
gauges (queued + sent-but-unacknowledged), ``repl_records_sent_total``,
``repl_bytes_sent_total``, ``repl_snapshots_total``,
``repl_snapshot_bytes_total``, ``repl_resume_total{mode=log|snapshot}``,
``repl_evictions_total{reason=...}``, ``repl_auth_failures_total``.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import hmac
import logging
import os
import threading
import time
from typing import Callable, Deque, Dict, Optional, Tuple

from photon_ml_tpu.chaos.injector import fault as _chaos_fault
from photon_ml_tpu.obs.pulse import clock as pulse_clock
from photon_ml_tpu.obs.pulse.context import delta_ctx as pulse_delta_ctx
from photon_ml_tpu.obs.pulse.context import forwarded as ctx_forwarded
from photon_ml_tpu.obs.pulse.context import to_wire as ctx_to_wire
from photon_ml_tpu.obs.trace import enabled as obs_enabled
from photon_ml_tpu.obs.trace import get_process_label
from photon_ml_tpu.obs.trace import instant as obs_instant
from photon_ml_tpu.online.delta_log import DeltaLog, DeltaRecord
from photon_ml_tpu.online.replication.snapshot import (SnapshotError,
                                                       pack_model_dir)
from photon_ml_tpu.online.replication.wire import (WireError,
                                                   encode_record_line,
                                                   parse_identity, parse_line)
from photon_ml_tpu.serving.frontend.protocol import (DEFAULT_MAX_LINE_BYTES,
                                                     BoundedLineReader,
                                                     LineTooLong, encode,
                                                     error_reply)

logger = logging.getLogger("photon_ml_tpu.online.replication")

_WAKE = object()  # queue sentinel: re-check floor/catch-up state


@dataclasses.dataclass(frozen=True)
class ReplicationConfig:
    """Owner-side replication policy knobs."""

    host: str = "127.0.0.1"
    port: int = 0  # 0 -> ephemeral; ReplicationServer.port holds the binding
    auth_token: Optional[str] = None
    max_line_bytes: int = DEFAULT_MAX_LINE_BYTES
    hello_timeout_s: float = 10.0
    # live fan-out queue bound per follower; overflow switches the
    # follower to log catch-up (it misses nothing — the log is durable)
    queue_records: int = 1024
    # retention-pin caps: a follower pinning sub-floor segments is evicted
    # when the pinned bytes pass pin_byte_cap or its last ack is older
    # than pin_age_cap_s
    pin_byte_cap: int = 64 << 20
    pin_age_cap_s: float = 300.0
    snapshot_chunk: int = 1 << 16
    housekeeping_interval_s: float = 15.0


class _Follower:
    """Per-subscriber state, owned by the event loop."""

    __slots__ = ("fid", "peer", "writer", "queue", "sent", "acked",
                 "acked_at", "floor", "need_catchup", "alive",
                 "queued_bytes", "unacked", "unacked_bytes", "evicted")

    def __init__(self, fid: int, peer: str,
                 writer: asyncio.StreamWriter, queue_bound: int):
        self.fid = fid
        self.peer = peer
        self.writer = writer
        self.queue: asyncio.Queue = asyncio.Queue(maxsize=queue_bound)
        self.sent: Optional[Tuple[int, int]] = None
        self.acked: Optional[Tuple[int, int]] = None
        self.acked_at = time.monotonic()
        self.floor: Optional[int] = None  # base generation the client holds
        self.need_catchup = True
        self.alive = True
        self.queued_bytes = 0
        # (identity, frame bytes) sent but not yet acknowledged
        self.unacked: Deque[Tuple[Tuple[int, int], int]] = collections.deque()
        self.unacked_bytes = 0
        self.evicted: Optional[str] = None  # eviction reason, once decided

    def pin_generation(self) -> Optional[int]:
        """Oldest generation this follower still needs from the log."""
        if self.acked is not None:
            return self.acked[0]
        return self.floor


class ReplicationServer:
    """Asyncio replication endpoint for one delta log (module docstring).

    ``snapshot_source`` returns the owner's current
    ``(model_dir, base_generation)`` — the directory the serving store was
    built from and the generation it was activated at.  For a trainer
    owner that never hot-swaps, the base generation is the floor below
    which no log record exists to a subscriber's benefit (usually 0: the
    whole log applies to the base).
    """

    def __init__(self, log: DeltaLog,
                 config: Optional[ReplicationConfig] = None,
                 snapshot_source: Optional[
                     Callable[[], Tuple[str, int]]] = None,
                 base_generation: int = 0,
                 registry=None):
        self.log = log
        self.config = config or ReplicationConfig()
        self._snapshot_source = snapshot_source
        self._registry = registry
        self._base_generation = int(base_generation)
        self._followers: Dict[int, _Follower] = {}
        self._fid_seq = 0
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._closed: Optional[asyncio.Event] = None
        self._closing = False
        self._housekeeper: Optional[asyncio.Task] = None
        self.port: Optional[int] = None
        # cross-thread view for the retention pin (compaction runs on the
        # trainer/swap thread): fid -> (pin generation, last ack monotonic)
        self._pin_lock = threading.Lock()
        self._pin_view: Dict[int, Tuple[Optional[int], float]] = {}

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> "ReplicationServer":
        self._loop = asyncio.get_running_loop()
        self._closed = asyncio.Event()
        self._server = await asyncio.start_server(
            self._on_connect, self.config.host, self.config.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self.log.add_listener(self._on_append)
        self.log.retention_pin = self.retention_floor
        self._housekeeper = asyncio.ensure_future(self._housekeeping())
        logger.info("photonrepl listening on %s:%d (queue %d records, pin "
                    "caps %d bytes / %.0fs)", self.config.host, self.port,
                    self.config.queue_records, self.config.pin_byte_cap,
                    self.config.pin_age_cap_s)
        return self

    async def wait_closed(self) -> None:
        await self._closed.wait()

    async def aclose(self) -> None:
        if self._closing:
            await self._closed.wait()
            return
        self._closing = True
        self.log.remove_listener(self._on_append)
        if self.log.retention_pin is self.retention_floor:
            self.log.retention_pin = None
        if self._housekeeper is not None:
            self._housekeeper.cancel()
        if self._server is not None:
            self._server.close()
        for f in list(self._followers.values()):
            self._close_follower(f)
        if self._server is not None:
            await self._server.wait_closed()
        self._closed.set()

    # -- owner-side hooks (foreign threads) --------------------------------
    def _on_append(self, record: DeltaRecord) -> None:
        """DeltaLog append listener — runs on the publisher's thread."""
        if self._loop is not None and not self._closing:
            self._loop.call_soon_threadsafe(self._fanout, record)

    def note_generation(self, generation: int) -> None:
        """The owner activated a new base (hot swap).  Raise the base
        floor and wake every sender so laggards drain + re-snapshot
        in-stream.  Thread-safe."""
        if self._loop is None:
            self._base_generation = max(self._base_generation,
                                        int(generation))
            return
        self._loop.call_soon_threadsafe(self._note_generation_locked,
                                        int(generation))

    def _note_generation_locked(self, generation: int) -> None:
        if generation <= self._base_generation:
            return
        self._base_generation = generation
        obs_instant("repl.generation", generation=generation,
                    followers=len(self._followers))
        for f in self._followers.values():
            self._nudge(f)

    def retention_floor(self) -> Optional[int]:
        """Compaction pin: the minimum generation a connected,
        well-behaved follower still needs — or None when nothing pins.
        Called from the owner's swap thread via ``DeltaLog.compact``;
        applies the byte/age caps and schedules evictions for followers
        that fail them."""
        now = time.monotonic()
        with self._pin_lock:
            pins = {fid: pin for fid, (pin, acked_at) in
                    self._pin_view.items()
                    if pin is not None and
                    now - acked_at <= self.config.pin_age_cap_s}
            stale = [fid for fid, (pin, acked_at) in self._pin_view.items()
                     if pin is not None and pin < self._base_generation and
                     now - acked_at > self.config.pin_age_cap_s]
        for fid in stale:
            self._evict(fid, "ack_age")
        while pins:
            floor = min(pins.values())
            if floor >= self._base_generation:
                return floor
            cost = sum(
                os.path.getsize(path)
                for gen, path in self.log.segments()
                if floor <= gen < self._base_generation
                and os.path.exists(path))
            if cost <= self.config.pin_byte_cap:
                return floor
            worst = min(pins, key=lambda fid: pins[fid])
            del pins[worst]
            self._evict(worst, "pin_bytes")
        return None

    def _evict(self, fid: int, reason: str) -> None:
        """Schedule an eviction from a foreign thread (idempotent)."""
        with self._pin_lock:
            self._pin_view.pop(fid, None)
        if self._registry is not None:
            self._registry.inc("repl_evictions_total", reason=reason)
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._evict_locked, fid, reason)

    def _evict_locked(self, fid: int, reason: str) -> None:
        f = self._followers.get(fid)
        if f is None or not f.alive:
            return
        f.evicted = reason
        logger.warning("photonrepl: evicting follower %s (%s) — it will "
                       "re-bootstrap from a snapshot", f.peer, reason)
        try:
            f.writer.write(encode({"repl": "restart", "reason": reason}))
        except (ConnectionError, OSError):
            pass
        self._close_follower(f)

    # -- loop-side state ---------------------------------------------------
    def _fanout(self, record: DeltaRecord) -> None:
        nbytes = len(record.encode())
        for f in self._followers.values():
            if not f.alive:
                continue
            try:
                f.queue.put_nowait(record)
                f.queued_bytes += nbytes
            except asyncio.QueueFull:
                # bounded backpressure: drop from the LIVE queue only —
                # the record is durable, the sender re-reads it from the
                # log once it catches up
                f.need_catchup = True
                self._nudge(f)
            self._lag_gauges(f)

    def _nudge(self, f: _Follower) -> None:
        try:
            f.queue.put_nowait(_WAKE)
        except asyncio.QueueFull:
            pass  # sender is already behind; it re-checks state anyway

    def _publish_pin(self, f: _Follower) -> None:
        with self._pin_lock:
            if f.alive:
                self._pin_view[f.fid] = (f.pin_generation(), f.acked_at)
            else:
                self._pin_view.pop(f.fid, None)

    def _lag_gauges(self, f: _Follower) -> None:
        if self._registry is None:
            return
        self._registry.set_gauge("repl_follower_lag_records",
                                 f.queue.qsize() + len(f.unacked),
                                 peer=f.peer)
        self._registry.set_gauge("repl_follower_lag_bytes",
                                 f.queued_bytes + f.unacked_bytes,
                                 peer=f.peer)

    def _close_follower(self, f: _Follower) -> None:
        if not f.alive:
            return
        f.alive = False
        self._followers.pop(f.fid, None)
        self._publish_pin(f)
        self._nudge(f)  # unblock a sender parked on queue.get()
        try:
            f.writer.close()
        except Exception:  # noqa: BLE001 — best-effort close
            pass
        if self._registry is not None:
            self._registry.set_gauge("repl_followers", len(self._followers))

    async def _housekeeping(self) -> None:
        """Periodic age-cap sweep so a silent follower is evicted even if
        the owner never swaps/compacts in between."""
        while True:
            await asyncio.sleep(self.config.housekeeping_interval_s)
            now = time.monotonic()
            for f in list(self._followers.values()):
                pin = f.pin_generation()
                if (pin is not None and pin < self._base_generation and
                        now - f.acked_at > self.config.pin_age_cap_s):
                    if self._registry is not None:
                        self._registry.inc("repl_evictions_total",
                                           reason="ack_age")
                    self._evict_locked(f.fid, "ack_age")

    # -- connection handling -----------------------------------------------
    async def _on_connect(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        peername = writer.get_extra_info("peername")
        peer = (f"{peername[0]}:{peername[1]}"
                if isinstance(peername, tuple) else str(peername))
        br = BoundedLineReader(reader.read, self.config.max_line_bytes)
        try:
            hello = await asyncio.wait_for(
                br.readline(), self.config.hello_timeout_s)
        except (asyncio.TimeoutError, LineTooLong,
                ConnectionError, OSError):
            writer.close()
            return
        try:
            ok, f = await self._subscribe(peer, hello, writer)
        except (ConnectionError, OSError):
            writer.close()
            return
        if not ok:
            return
        if self._registry is not None:
            self._registry.set_gauge("repl_followers", len(self._followers))
        sender = asyncio.ensure_future(self._sender(f))
        try:
            await self._acks(f, br)
        finally:
            self._close_follower(f)
            sender.cancel()
            try:
                await sender
            except (asyncio.CancelledError, ConnectionError, OSError):
                pass

    async def _subscribe(self, peer: str, hello: Optional[bytes],
                         writer: asyncio.StreamWriter,
                         ) -> Tuple[bool, Optional[_Follower]]:
        async def _refuse(msg: str) -> Tuple[bool, None]:
            writer.write(encode(error_reply(msg)))
            await writer.drain()
            writer.close()
            return False, None

        if hello is None:
            writer.close()
            return False, None
        t1 = pulse_clock.now_ns()  # hello receipt — clock ping-pong leg
        try:
            obj = parse_line(hello)
            last = parse_identity(obj.get("last"))
        except WireError as e:
            return await _refuse(str(e))
        if obj.get("cmd") != "subscribe":
            return await _refuse(f"expected subscribe, got "
                                 f"{obj.get('cmd')!r}")
        if self.config.auth_token is not None:
            token = obj.get("token")
            token = token if isinstance(token, str) else ""
            if not hmac.compare_digest(token.encode("utf-8"),
                                       self.config.auth_token.encode(
                                           "utf-8")):
                if self._registry is not None:
                    self._registry.inc("repl_auth_failures_total")
                logger.warning("photonrepl: rejected unauthenticated "
                               "subscriber %s", peer)
                return await _refuse("unauthorized")
        floor = obj.get("floor")
        floor = int(floor) if isinstance(floor, (int, float)) else None
        mode = self._decide_resume(last, floor)
        if mode == "snapshot" and self._snapshot_source is None:
            return await _refuse("snapshot bootstrap unavailable "
                                 "(owner has no snapshot source)")
        self._fid_seq += 1
        f = _Follower(self._fid_seq, peer, writer,
                      self.config.queue_records)
        if mode == "log":
            f.floor = floor
            f.sent = last
            f.acked = last  # the client TOLD us it applied this much
        # register before replying: the retention pin must see this
        # follower before its first catch-up read races a compaction
        self._followers[f.fid] = f
        self._publish_pin(f)
        if self._registry is not None:
            self._registry.inc("repl_resume_total", mode=mode)
        obs_instant("repl.subscribe", peer=peer, mode=mode)
        logger.info("photonrepl: subscriber %s resume mode=%s last=%s "
                    "floor=%s", peer, mode, last, floor)
        resume = {"repl": "resume", "mode": mode,
                  "generation": self._base_generation,
                  "floor": self._base_generation}
        t0 = obj.get("t0")
        if isinstance(t0, int):
            # complete the photonpulse clock ping-pong piggybacked on the
            # subscribe hello: echo t0, stamp receipt (t1) and send (t2)
            resume["who"] = get_process_label() or "owner"
            resume["t0"] = t0
            resume["t1"] = t1
            resume["t2"] = pulse_clock.now_ns()
        writer.write(encode(resume))
        await writer.drain()
        return True, f

    def _decide_resume(self, last: Optional[Tuple[int, int]],
                       floor: Optional[int]) -> str:
        """Log replay when the client's base lineage matches and the
        retained log covers its identity; snapshot otherwise."""
        if floor is None or floor != self._base_generation:
            return "snapshot"
        log_last = self.log.last_identity()
        if last is None:
            return "log"  # has the base, applied nothing: replay all
        if log_last is None or last > log_last:
            return "snapshot"  # claims records this log never wrote
        if last[0] < floor:
            return "snapshot"  # inconsistent client state
        min_gen = self.log.min_retained_generation()
        if min_gen is not None and last[0] < min_gen:
            return "snapshot"  # compaction passed it
        return "log"

    # -- acks --------------------------------------------------------------
    async def _acks(self, f: _Follower, br: BoundedLineReader) -> None:
        while f.alive:
            try:
                line = await br.readline()
            except LineTooLong:
                continue  # stream realigned; drop the garbage line
            except (ConnectionError, OSError):
                return
            if line is None:
                return
            if not line.strip():
                continue
            try:
                obj = parse_line(line)
                if obj.get("cmd") != "ack":
                    continue
                acked = parse_identity(obj.get("last"))
            except WireError:
                continue
            if acked is None:
                continue
            f.acked = acked
            f.acked_at = time.monotonic()
            while f.unacked and f.unacked[0][0] <= acked:
                _, nbytes = f.unacked.popleft()
                f.unacked_bytes -= nbytes
            self._publish_pin(f)
            self._lag_gauges(f)

    # -- sending -----------------------------------------------------------
    async def _sender(self, f: _Follower) -> None:
        try:
            while f.alive:
                base = self._base_generation
                if f.floor is None or f.floor < base:
                    # the client's base is behind: drain the pre-swap
                    # records it can still use (pinned segments), then
                    # ship the new base inline
                    if f.floor is not None:
                        if not await self._catchup(f, lo=f.floor, hi=base):
                            return
                    if not await self._ship_snapshot(f):
                        return
                    continue
                if f.need_catchup:
                    f.need_catchup = False
                    if not await self._catchup(f, lo=f.floor, hi=None):
                        return
                    continue
                rec = await f.queue.get()
                if rec is _WAKE or not f.alive:
                    continue
                f.queued_bytes -= len(rec.encode())
                if f.sent is not None and rec.identity <= f.sent:
                    continue  # already delivered via log catch-up
                if rec.generation < f.floor:
                    continue  # superseded by the base the client holds
                await self._send_record(f, rec)
        except (ConnectionError, OSError):
            pass
        finally:
            self._close_follower(f)

    async def _send_record(self, f: _Follower, rec: DeltaRecord) -> None:
        act = _chaos_fault("repl.server.send")
        if act is not None:
            # chaos seams, in the follower's terms: "drop" = the TCP
            # session dies mid-stream (client reconnects and resumes);
            # "stall"/"stall_dist" = a slow owner (client ack timer keeps
            # ticking; stall_dist holds are sampled by the injector);
            # "garbage" = a corrupt frame on the wire IN PLACE of the
            # record (client must fail typed, reconnect, and recover the
            # record via log catch-up — f.sent is not advanced)
            if act.kind in ("stall", "stall_dist"):
                await asyncio.sleep(float(act.data.get("stall_s", 0.05)))
            elif act.kind == "garbage":
                f.writer.write(b"\x7f{not json//\n")
                await f.writer.drain()
                return
            else:
                raise act.to_error()
        tp = None
        if obs_enabled():
            # the trace context rides BESIDE the payload ("tp" field), so
            # the record bytes stay bit-identical to the owner's frame
            ctx = pulse_delta_ctx(rec.identity)
            if ctx is not None:
                tp = ctx_to_wire(ctx_forwarded(ctx))
        line = encode_record_line(rec, tp=tp)
        f.writer.write(line)
        await f.writer.drain()
        f.sent = rec.identity
        f.unacked.append((rec.identity, len(line)))
        f.unacked_bytes += len(line)
        if self._registry is not None:
            self._registry.inc("repl_records_sent_total")
            self._registry.inc("repl_bytes_sent_total", len(line))
        self._lag_gauges(f)

    async def _catchup(self, f: _Follower, lo: Optional[int],
                       hi: Optional[int]) -> bool:
        """Send every retained record after ``f.sent`` with generation in
        ``[lo, hi)`` (``hi=None`` -> unbounded).  Returns False when the
        follower can no longer be served from the log (restart sent)."""
        lo = lo or 0
        need_gen = f.sent[0] if f.sent is not None else lo
        min_gen = self.log.min_retained_generation()
        if (need_gen < self._base_generation and min_gen is not None
                and need_gen < min_gen):
            # compaction passed this follower mid-connection (pin caps
            # evicted it, or it subscribed in a lost race): it cannot be
            # caught up from the log any more
            f.evicted = f.evicted or "compacted"
            if self._registry is not None:
                self._registry.inc("repl_evictions_total",
                                   reason="compacted")
            f.writer.write(encode({"repl": "restart",
                                   "reason": "compacted"}))
            await f.writer.drain()
            return False
        sent_from = f.sent

        def _scan():
            out = []
            for rec in self.log.replay(after=sent_from):
                if rec.generation < lo:
                    continue
                if hi is not None and rec.generation >= hi:
                    continue
                out.append(rec)
            return out

        records = await asyncio.get_running_loop().run_in_executor(
            None, _scan)
        for rec in records:
            if not f.alive:
                return False
            await self._send_record(f, rec)
        return True

    async def _ship_snapshot(self, f: _Follower) -> bool:
        """Pack the owner's current base and stream it inline.  After this
        the follower's floor is the shipped base generation and catch-up
        resumes from the log at that floor."""
        assert self._snapshot_source is not None
        loop = asyncio.get_running_loop()
        model_dir, gen = self._snapshot_source()
        for _ in range(3):
            try:
                data, crc = await loop.run_in_executor(
                    None, pack_model_dir, model_dir)
            except SnapshotError as e:
                logger.error("photonrepl: snapshot pack failed: %s", e)
                f.writer.write(encode(error_reply(f"snapshot failed: {e}")))
                await f.writer.drain()
                return False
            again_dir, again_gen = self._snapshot_source()
            if (again_dir, again_gen) == (model_dir, gen):
                break
            model_dir, gen = again_dir, again_gen  # swapped mid-pack: retry
        f.writer.write(encode({
            "repl": "snapshot", "bytes": len(data), "crc32": crc,
            "generation": gen, "version": os.path.basename(
                os.path.normpath(model_dir))}))
        for off in range(0, len(data), self.config.snapshot_chunk):
            act = _chaos_fault("repl.server.snapshot")
            if act is not None:
                # mid-snapshot disconnect: the follower sees a short read
                # against the announced byte count, fails its CRC/length
                # check, and re-bootstraps on reconnect
                raise act.to_error()
            f.writer.write(data[off: off + self.config.snapshot_chunk])
            await f.writer.drain()
        f.floor = gen
        f.need_catchup = True
        if self._registry is not None:
            self._registry.inc("repl_snapshots_total")
            self._registry.inc("repl_snapshot_bytes_total", len(data))
        obs_instant("repl.snapshot", peer=f.peer, generation=gen,
                    nbytes=len(data))
        logger.info("photonrepl: shipped snapshot gen %d (%d bytes) to %s",
                    gen, len(data), f.peer)
        return True


class ThreadedReplicationServer:
    """Run a ReplicationServer on a dedicated event-loop thread (the
    ``ThreadedFrontend`` pattern): ``start()`` blocks until the socket is
    bound, ``stop()`` closes and joins.  This is what blocking callers —
    ``cli/learn.py``, the bench, tests — use."""

    def __init__(self, log: DeltaLog,
                 config: Optional[ReplicationConfig] = None,
                 snapshot_source: Optional[
                     Callable[[], Tuple[str, int]]] = None,
                 base_generation: int = 0,
                 registry=None):
        self.server = ReplicationServer(
            log, config, snapshot_source=snapshot_source,
            base_generation=base_generation, registry=registry)
        self._ready = threading.Event()
        self._error: Optional[BaseException] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="photonrepl")

    @property
    def port(self) -> int:
        return self.server.port

    def note_generation(self, generation: int) -> None:
        self.server.note_generation(generation)

    def _run(self) -> None:
        try:
            asyncio.run(self._main())
        except BaseException as e:  # startup failures surface in start()
            self._error = e
            self._ready.set()

    async def _main(self) -> None:
        try:
            await self.server.start()
        except BaseException as e:
            self._error = e
            self._ready.set()
            raise
        self._loop = asyncio.get_running_loop()
        self._ready.set()
        await self.server.wait_closed()

    def start(self, timeout: float = 30.0) -> "ThreadedReplicationServer":
        self._thread.start()
        if not self._ready.wait(timeout):
            raise RuntimeError(
                f"replication server did not start within {timeout}s")
        if self._error is not None:
            raise RuntimeError(
                "replication server failed to start") from self._error
        return self

    def stop(self, timeout: float = 30.0) -> None:
        if self._loop is not None and self._thread.is_alive():
            asyncio.run_coroutine_threadsafe(self.server.aclose(),
                                             self._loop)
        self._thread.join(timeout)


def attach_replication(swapper, config: Optional[ReplicationConfig] = None,
                       registry=None) -> ThreadedReplicationServer:
    """Start a :class:`ThreadedReplicationServer` wired to a log-owning
    ``serving.HotSwapper``: snapshots come from the swapper's serving base
    (``serving_base()`` — the atomic ``(model_dir, floor)`` pair), and a
    successful hot swap raises the server's base floor in-stream via the
    swapper's ``on_swap`` hook (chained, not replaced).  This is the one
    call sites use — ``cli/learn.py --repl-listen``, the bench, tests."""
    if swapper.delta_log is None or not swapper.log_owner:
        raise ValueError("replication needs a swapper that OWNS a delta "
                         "log (delta_log=..., log_owner=True)")
    srv = ThreadedReplicationServer(
        swapper.delta_log, config,
        snapshot_source=swapper.serving_base,
        base_generation=swapper.replay_floor,
        registry=registry)
    # a replicated owner's hot swap must leave its live state derivable as
    # ``snapshot dir + retained records >= floor`` — so the incoming base
    # supersedes pre-swap records instead of having them replayed onto it
    # (serving/swap.py __init__ for the full argument)
    swapper.base_supersedes_log = True
    prev = swapper.on_swap

    def _on_swap(model_dir: str, generation: int) -> None:
        if prev is not None:
            prev(model_dir, generation)
        srv.note_generation(generation)

    swapper.on_swap = _on_swap
    return srv.start()
