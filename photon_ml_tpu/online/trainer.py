"""Incremental per-entity trainer: fresh mini-batches -> live coefficients.

Photon ML reference counterpart: the paper's §"online learning" argument —
random-effect models are per-entity and tiny, so they can (and should)
refresh far more often than the shared fixed-effect model.  The reference
repo retrains offline; this module is the missing producer for the
serving stack's delta machinery (serving/swap.py, online/delta_log.py).

The refit is the GLMix per-entity subproblem verbatim: for each entity
with fresh examples, minimize ``sum_i w_i * loss(x_i . beta + offset_i,
y_i) + l2/2 ||beta||^2`` where ``offset_i`` carries the example offset
PLUS every OTHER coordinate's margin (the coordinate-descent contract —
game/coordinate.py does exactly this on the batch path).  Three choices
make it cheap enough to run continuously (Snap ML's thesis, PAPERS.md):

- **warm start** from the SERVED coefficients (``dense_row``): the fresh
  mini-batch moves the optimum a little, so Newton from the live row
  converges in a couple of iterations instead of from scratch;
- **batched tiny solves**: entities become lanes of one
  ``opt/newton_soa.py`` SoA program ([d, L] lanes-last — the layout built
  for exactly these narrow per-entity systems; Pallas-eligible on TPU for
  free), padded to a pow2 (cap, L) grid so the jit cache stays a handful
  of entries;
- **in-process publish**: updated rows go straight to
  ``HotSwapper.publish_delta`` — device scatter + durable log append under
  one identity, no serialization hop (the Spark-perf study's data-movement
  tax is the thing this path deletes).

Serving stays zero-recompile: a published row is a same-shape scatter
into the live table, and the solver jit cache is keyed on padded shapes
the pow2 floors bound.

``consume`` is the whole API: parse examples, group by entity, refit every
eligible coordinate, publish.  Single-threaded by contract (one trainer
per process — the swapper's lock already serializes publishes; the solver
cache is not locked).
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from photon_ml_tpu.core.losses import PointwiseLoss, loss_for_task
from photon_ml_tpu.obs.pulse.context import bind as ctx_bind
from photon_ml_tpu.obs.pulse.context import mint as ctx_mint
from photon_ml_tpu.obs.trace import enabled as obs_enabled
from photon_ml_tpu.obs.trace import span as obs_span
from photon_ml_tpu.opt.newton_soa import soa_eligible, solve_newton_soa
from photon_ml_tpu.opt.types import SolverConfig
from photon_ml_tpu.serving.batcher import (Request, densify_features,
                                           request_from_json)
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                     FixedCoordinate,
                                                     RandomCoordinate)

logger = logging.getLogger("photon_ml_tpu.online.trainer")


@dataclasses.dataclass(frozen=True)
class Example:
    """One labeled fresh example: a scoring Request plus its outcome."""

    request: Request
    label: float
    weight: float = 1.0


def example_from_json(obj: dict) -> Example:
    """Wire JSON -> Example.  The request part is the serving wire format
    (``request_from_json``); the label rides as ``label`` or ``response``
    (the TrainingExampleAvro field name), weight defaults to 1."""
    label = obj.get("label", obj.get("response"))
    if label is None:
        raise ValueError("example needs a 'label' (or 'response') field")
    return Example(request=request_from_json(obj), label=float(label),
                   weight=float(obj.get("weight", 1.0)))


@dataclasses.dataclass(frozen=True)
class TrainerConfig:
    """Refit knobs.

    ``coordinates``: which coordinates to refit (None = every
    random-effect coordinate the SoA gate accepts; naming an ineligible
    one raises at construction).  ``l2``: per-entity ridge strength —
    also the prior pulling a sparsely-observed entity toward its
    warm-start row... the regularizer is centered at 0 exactly like batch
    training, so l2 trades batch-parity pull-to-zero against mini-batch
    overfit.  ``cap_floor``/``lane_floor``: pow2 padding floors for the
    solve grid (row capacity x entity lanes) — they bound the solver jit
    cache for arbitrary mini-batch shapes.  ``min_rows_per_entity``:
    entities with fewer fresh rows wait for more data instead of being
    refit on noise."""

    coordinates: Optional[Tuple[str, ...]] = None
    l2: float = 1.0
    max_iters: int = 20
    tolerance: float = 1e-7
    min_rows_per_entity: int = 1
    cap_floor: int = 4
    lane_floor: int = 8


@dataclasses.dataclass
class RefitReport:
    """What one ``consume`` call did."""

    examples: int = 0
    entities: int = 0            # entity-coordinate refits solved
    rows: int = 0                # example-rows that entered a solve
    published: int = 0
    rejected: int = 0            # publish refused by the store
    skipped_unknown: int = 0     # example rows with no trained entity row
    coordinates: Dict[str, int] = dataclasses.field(default_factory=dict)
    first_identity: Optional[Tuple[int, int]] = None
    last_identity: Optional[Tuple[int, int]] = None
    solve_s: float = 0.0
    publish_s: float = 0.0
    wall_s: float = 0.0
    publish_started: float = 0.0  # perf_counter at first publish

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        out.pop("publish_started")
        for k in ("solve_s", "publish_s", "wall_s"):
            out[k] = round(out[k], 6)
        return out


def _pow2_at_least(n: int, floor: int) -> int:
    p = max(1, floor)
    while p < n:
        p *= 2
    return p


class IncrementalTrainer:
    """Mini-batch per-entity refits published through a HotSwapper.

    ``swapper`` is the publish sink (``publish_delta`` — live store apply
    + delta-log append under one identity).  Attach the log to the
    swapper, not here: the trainer only ever sees identities.
    """

    def __init__(self, swapper, config: Optional[TrainerConfig] = None,
                 metrics=None):
        self.swapper = swapper
        self.engine = swapper.engine
        self.config = config or TrainerConfig()
        self.metrics = metrics or self.engine.metrics
        self._solvers: Dict[tuple, object] = {}
        self._warned_skip: set = set()
        self._validate_targets(self.engine.store)

    # -- target selection --------------------------------------------------
    def _validate_targets(self, store: CoefficientStore) -> None:
        loss = loss_for_task(store.task)
        if self.config.coordinates is None:
            return  # auto mode validates (and warns) per consume
        for cid in self.config.coordinates:
            c = store.coordinates.get(cid)
            if not isinstance(c, RandomCoordinate):
                raise ValueError(
                    f"online refit target {cid!r} is not a random-effect "
                    "coordinate of the served model")
            if not soa_eligible(c.dim, loss.name):
                raise ValueError(
                    f"online refit target {cid!r} (dim {c.dim}, loss "
                    f"{loss.name!r}) is outside the batched SoA solver's "
                    "gate — online refit targets narrow per-entity models")

    def _targets(self, store: CoefficientStore,
                 loss: PointwiseLoss) -> List[RandomCoordinate]:
        out = []
        wanted = self.config.coordinates
        for cid in store.order:
            c = store.coordinates[cid]
            if not isinstance(c, RandomCoordinate):
                continue
            if wanted is not None and cid not in wanted:
                continue
            if not soa_eligible(c.dim, loss.name):
                if wanted is not None:
                    raise ValueError(
                        f"online refit target {cid!r} became ineligible "
                        f"(dim {c.dim}, loss {loss.name!r})")
                if cid not in self._warned_skip:
                    self._warned_skip.add(cid)
                    logger.warning(
                        "online refit: skipping coordinate %r (dim %d, "
                        "loss %r outside the SoA gate)", cid, c.dim,
                        loss.name)
                continue
            out.append(c)
        return out

    # -- solver cache ------------------------------------------------------
    def _solver(self, loss: PointwiseLoss, d: int, cap: int, lanes: int):
        key = (loss.name, d, cap, lanes)
        fn = self._solvers.get(key)
        if fn is None:
            cfg = SolverConfig(max_iters=self.config.max_iters,
                               tolerance=self.config.tolerance,
                               track_states=False)

            def run(w0_t, x_t, y_t, off_t, wt_t, l2):
                return solve_newton_soa(loss, w0_t, x_t, y_t, off_t, wt_t,
                                        l2, cfg)

            fn = self._solvers[key] = jax.jit(run)
        return fn

    # -- the loop body -----------------------------------------------------
    def consume(self, examples: Sequence[Union[Example, dict]],
                ) -> RefitReport:
        """Refit every target coordinate on one mini-batch and publish.

        Accepts ``Example`` objects or their wire-JSON dicts.  Returns the
        per-batch report; publishes nothing for coordinates/entities the
        batch doesn't touch."""
        t_wall = time.perf_counter()
        exs = [e if isinstance(e, Example) else example_from_json(e)
               for e in examples]
        report = RefitReport(examples=len(exs))
        if not exs:
            return report
        store = self.engine.store
        loss = loss_for_task(store.task)
        targets = self._targets(store, loss)
        if not targets:
            report.wall_s = time.perf_counter() - t_wall
            return report
        requests = [e.request for e in exs]
        n = len(requests)
        mats = densify_features(requests, store.index_maps, n,
                                dtype=store.config.x_dtype)

        # every coordinate's margin per example, so each refit's offset can
        # carry "everything but me" — the coordinate-descent contract
        margins: Dict[str, np.ndarray] = {}
        eids_of: Dict[str, np.ndarray] = {}
        for cid in store.order:
            c = store.coordinates[cid]
            x = mats[c.feature_shard]
            if isinstance(c, FixedCoordinate):
                margins[cid] = x @ np.asarray(c.weights)
                continue
            eids = np.fromiter(
                (store.entity_id(c.random_effect_type,
                                 r.ids.get(c.random_effect_type))
                 for r in requests), np.int64, n)
            eids_of[cid] = eids
            m = np.zeros(n, np.float64)
            for i in range(n):
                if eids[i] >= 0:
                    row = c.dense_row(int(eids[i]))
                    if row is not None:
                        m[i] = float(x[i] @ row)
            margins[cid] = m
        base = np.asarray([r.offset for r in requests], np.float64)
        total = base + sum(margins.values())

        for c in targets:
            self._refit_coordinate(c, exs, mats[c.feature_shard],
                                   eids_of[c.cid],
                                   total - margins[c.cid], loss, report)
        report.wall_s = time.perf_counter() - t_wall
        return report

    def _refit_coordinate(self, c: RandomCoordinate, exs: List[Example],
                          x: np.ndarray, eids: np.ndarray,
                          offsets: np.ndarray, loss: PointwiseLoss,
                          report: RefitReport) -> None:
        groups: Dict[int, List[int]] = {}
        names: Dict[int, str] = {}
        for i, e in enumerate(exs):
            eid = int(eids[i])
            if eid < 0 or c.dense_row(eid) is None:
                report.skipped_unknown += 1
                continue
            groups.setdefault(eid, []).append(i)
            names[eid] = e.request.ids[c.random_effect_type]
        groups = {eid: rows for eid, rows in groups.items()
                  if len(rows) >= self.config.min_rows_per_entity}
        if not groups:
            return
        lanes = sorted(groups)
        n_lanes, cap_real = len(lanes), max(map(len, groups.values()))
        cap = _pow2_at_least(cap_real, self.config.cap_floor)
        lanes_pad = _pow2_at_least(n_lanes, self.config.lane_floor)
        d = c.dim
        dt = np.float32
        w0_t = np.zeros((d, lanes_pad), dt)
        x_t = np.zeros((cap, d, lanes_pad), dt)
        y_t = np.zeros((cap, lanes_pad), dt)
        off_t = np.zeros((cap, lanes_pad), dt)
        wt_t = np.zeros((cap, lanes_pad), dt)
        l2 = np.full(lanes_pad, self.config.l2, dt)
        for j, eid in enumerate(lanes):
            w0_t[:, j] = c.dense_row(eid)  # warm start from served rows
            for r_i, i in enumerate(groups[eid]):
                x_t[r_i, :, j] = x[i]
                y_t[r_i, j] = exs[i].label
                off_t[r_i, j] = offsets[i]
                wt_t[r_i, j] = exs[i].weight
        rows_used = sum(map(len, groups.values()))

        t0 = time.perf_counter()
        with obs_span("online.refit", coordinate=c.cid, entities=n_lanes,
                      rows=rows_used, cap=cap, lanes=lanes_pad):
            solver = self._solver(loss, d, cap, lanes_pad)
            res = solver(w0_t, x_t, y_t, off_t, wt_t, l2)
            w = np.asarray(res.w)  # [d, lanes_pad]; host sync ends the span
        solve_s = time.perf_counter() - t0
        report.solve_s += solve_s
        report.entities += n_lanes
        report.rows += rows_used
        report.coordinates[c.cid] = (
            report.coordinates.get(c.cid, 0) + n_lanes)

        reg = self.metrics.registry
        reg.inc("online_refit_entities_total", n_lanes)
        reg.inc("online_refit_rows_total", rows_used)
        reg.observe("online_refit_s", solve_s)

        t_pub = time.perf_counter()
        if not report.publish_started:
            report.publish_started = t_pub
        # one trace context per publish wave: it is minted HERE (the pod
        # slice's write admission point), stamped on the owner's publish
        # span, carried on the replication wire, and closed out by each
        # replica's online.store_visible instant
        bound = (ctx_bind(ctx_mint()) if obs_enabled()
                 else contextlib.nullcontext())
        with bound, obs_span("online.publish", coordinate=c.cid,
                             entities=n_lanes):
            for j, eid in enumerate(lanes):
                t_row = time.perf_counter()
                ident = self.swapper.publish_delta(c.cid, names[eid],
                                                   w[:, j])
                if ident is None:
                    report.rejected += 1
                    continue
                # publish -> visible: apply_delta returned, so the next
                # resolve on ANY tier serves the new row
                reg.observe("online_publish_visible_s",
                            time.perf_counter() - t_row)
                report.published += 1
                if report.first_identity is None:
                    report.first_identity = ident
                report.last_identity = ident
        report.publish_s += time.perf_counter() - t_pub
