"""Replicated catch-up: replay the delta log into a CoefficientStore.

Two consumers share this machinery (module docstring of ``delta_log``):

- **swap-in replay** (serving/swap.py): a freshly rotated-in generation
  replays the log before ``activate`` so the flip never loses rows the
  online trainer published while the snapshot was training/loading;
- **replica follow** (``LogFollower``, ``cli/serve.py --delta-log``): a
  second serving process applies the same ordered stream to its own store
  and converges to the writer's coefficient state.

**Idempotence.**  Replay tracks the last applied identity
``(generation, delta_version)`` and skips anything at or below it, so
overlapping replays (duplicated iterators, a follower restarted from
scratch, a full-log replay after a partial one) apply each update once.
The position is the LOG's identity, never the local store's generation —
generation numbers are process-local counters and mean nothing across
processes.

**Ordering = correctness.**  Records are full-row replacements, so
applying a prefix of the log in order always yields a state the writer
actually had; applying the whole log yields the writer's current rows
bitwise (tests/test_online.py asserts this).

**Replication-transparent.**  Replay goes through ``store.apply_delta``,
which scatters each record to EVERY device row holding its entity (hot-row
replication, serving/coefficient_store) — so a replica whose traffic-aware
rebalance placed an entity on different shards, or replicated it when the
writer did not, still converges to the writer's COEFFICIENTS bitwise.
Placement is process-local policy; the log carries only rows.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import (TYPE_CHECKING, Callable, Iterable, Optional,
                    Tuple)

import numpy as np

from photon_ml_tpu.obs.pulse.context import bind as ctx_bind
from photon_ml_tpu.obs.pulse.context import delta_ctx as pulse_delta_ctx
from photon_ml_tpu.obs.registry import MetricsRegistry
from photon_ml_tpu.obs.trace import enabled as obs_enabled
from photon_ml_tpu.obs.trace import instant as obs_instant
from photon_ml_tpu.obs.trace import span as obs_span
from photon_ml_tpu.online.delta_log import DeltaLog, DeltaRecord

if TYPE_CHECKING:  # import-cycle guard: serving.swap imports this module
    from photon_ml_tpu.serving.coefficient_store import CoefficientStore

logger = logging.getLogger("photon_ml_tpu.online.catchup")


@dataclasses.dataclass
class CatchupStats:
    """One replay pass: what was applied, skipped, or refused."""

    applied: int = 0
    skipped: int = 0   # identity at or below the replay position
    rejected: int = 0  # unknown entity / unknown coordinate / bad width
    position: Optional[Tuple[int, int]] = None  # last identity consumed

    def merge(self, other: "CatchupStats") -> None:
        self.applied += other.applied
        self.skipped += other.skipped
        self.rejected += other.rejected
        if other.position is not None:
            self.position = other.position


def replay_into_store(store: "CoefficientStore",
                      records: Iterable[DeltaRecord],
                      position: Optional[Tuple[int, int]] = None,
                      registry: Optional[MetricsRegistry] = None,
                      ) -> CatchupStats:
    """Apply an ordered record stream to a store; never raises.

    ``position`` is the last identity already applied (None = apply all);
    records at or below it are skipped, making any overlap idempotent.  A
    record the store refuses — entity or coordinate the snapshot never
    trained, row width mismatch after a schema change — is counted and
    logged, not fatal: a replica must survive replaying a log written
    against a slightly different snapshot.
    """
    stats = CatchupStats(position=position)
    for r in records:
        if stats.position is not None and r.identity <= stats.position:
            stats.skipped += 1
            continue
        try:
            ok = store.apply_delta(r.cid, r.entity,
                                   np.asarray(r.row, dtype=np.float64))
        except ValueError as e:
            logger.warning("catchup: record %s rejected: %s", r.identity, e)
            ok = False
        if ok:
            stats.applied += 1
            if obs_enabled():
                # the end of the publish's causal chain: the update the
                # owner traced is now visible in THIS process's store
                ctx = pulse_delta_ctx(r.identity)
                if ctx is not None:
                    with ctx_bind(ctx):
                        obs_instant("online.store_visible",
                                    generation=r.generation,
                                    version=r.delta_version)
        else:
            stats.rejected += 1
        stats.position = r.identity
    if registry is not None and (stats.applied or stats.rejected):
        registry.inc("catchup_applied_total", stats.applied)
        if stats.rejected:
            registry.inc("catchup_rejected_total", stats.rejected)
    return stats


class LogFollower:
    """Tail a delta log and keep a follower store converged.

    ``store_getter`` returns the store to apply to on each pass — pass
    ``lambda: engine.store`` so a hot swap in the follower process
    retargets the follow loop automatically.  When the store's generation
    changes between passes the position resets and the WHOLE log replays
    into the new store: replay is an ordered overwrite, so the result
    matches the writer regardless of what the swapped-in snapshot already
    contained, and compaction keeps the log short enough for this to be
    cheap.

    ``run_once`` is the synchronous form (tests, initial catch-up before
    serving); ``start``/``stop`` run it on a daemon thread at
    ``poll_interval_s``.
    """

    def __init__(self, log: DeltaLog,
                 store_getter: Callable[[], "CoefficientStore"],
                 poll_interval_s: float = 0.05,
                 registry: Optional[MetricsRegistry] = None,
                 backoff_max_s: float = 2.0):
        self.log = log
        self._store_getter = store_getter
        self.poll_interval_s = poll_interval_s
        self.backoff_max_s = backoff_max_s
        self._registry = registry
        self._position: Optional[Tuple[int, int]] = None
        self._store_generation: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._run_lock = threading.Lock()
        # follow-loop health (chaos.health reads these): a persistently
        # failing pass must be VISIBLE, not a quiet hot loop
        self.errors_total = 0
        self.consecutive_errors = 0
        self.last_success_at: Optional[float] = None
        # optional chaos.health.WorkerWatch — wraps each pass so the
        # watchdog can tell "wedged mid-pass" from "idle between polls"
        self.watch = None

    @property
    def position(self) -> Optional[Tuple[int, int]]:
        # photonlint: disable=alias-escape -- the position is an
        # immutable (generation, offset) tuple the catch-up pass
        # REPLACES under _run_lock, never mutates in place
        return self._position

    @property
    def worker_thread(self) -> Optional[threading.Thread]:
        """The follow-loop thread (None before ``start``) — what a
        chaos.health.Watchdog registers."""
        return self._thread

    def run_once(self) -> CatchupStats:
        """One catch-up pass: apply everything past the current position."""
        with self._run_lock:
            store = self._store_getter()
            if store.generation != self._store_generation:
                # new local snapshot: full ordered replay re-derives the
                # writer's state on it (idempotent overwrite — see class doc)
                self._position = None
                self._store_generation = store.generation
            with obs_span("online.catchup", generation=store.generation):
                stats = replay_into_store(store, self.log.replay(),
                                          position=self._position,
                                          registry=self._registry)
            if stats.position is not None:
                self._position = stats.position
            self.last_success_at = time.monotonic()
            self.consecutive_errors = 0
            return stats

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="photon-delta-follow")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        # Exponential backoff on failure (capped, reset on success): a
        # persistently broken log must not spin a hot error loop at the
        # poll interval, and every failed pass is counted — silence here
        # is exactly the failure mode photonlint PL009 flags.
        delay = self.poll_interval_s
        while not self._stop.is_set():
            try:
                if self.watch is not None:
                    with self.watch.busy():
                        self.run_once()
                else:
                    self.run_once()
                delay = self.poll_interval_s
            except Exception:
                with self._run_lock:
                    self.errors_total += 1
                    self.consecutive_errors += 1
                if self._registry is not None:
                    self._registry.inc("catchup_follow_errors_total")
                delay = min(max(delay, self.poll_interval_s) * 2,
                            self.backoff_max_s)
                logger.exception(
                    "catchup: follow pass failed (%d consecutive); "
                    "retrying in %.2fs", self.consecutive_errors, delay)
            self._stop.wait(delay)
