"""Durable append-only delta log: the replication spine of photonlearn.

Photon ML reference counterpart: none in the batch repo — LinkedIn's
production GLMix pushes retrained PalDB stores through offline
infrastructure.  The paper's operational point (random effects must
refresh far more often than the fixed effect) needs a durable, ordered
carrier for single-row coefficient updates, which is this file.

**Identity.**  Every record is keyed by the serving swapper's
``(generation, delta_version)`` pair — the ONE version identity the
coefficient state already has (serving/swap.py).  The log requires
identities to be strictly increasing in lexicographic order, which the
single-writer swapper guarantees (one swap OR delta in flight at a time)
and the log enforces loudly, because every replay consumer depends on it
for idempotence: a follower remembers the last identity it applied and
skips anything at or below it.

**Format.**  One directory per log.  Segment files, one per generation
(``segment-<generation>.log``), each starting with an 8-byte magic and
holding length-prefixed records::

    [u32 payload_len][u32 crc32(payload)][payload bytes]   (little-endian)

The payload is compact JSON — ``{"g": generation, "v": delta_version,
"c": cid, "e": entity, "r": [row...]}``.  JSON float round-trips are
exact (repr shortest-round-trip), and the store casts rows back to the
archive dtype on apply, so a replayed row is bitwise the published row.

**Crash safety.**  A crash mid-append leaves at most one torn record at
the tail of the newest segment.  ``replay`` treats any framing violation
— short header, length past EOF, CRC mismatch, undecodable payload — as
the torn tail: it stops that segment cleanly and NEVER raises.  A writer
re-opening a segment first truncates it to the last valid record, so new
appends never land after garbage that replay would refuse to cross.

**Compaction.**  At a swap boundary the new snapshot supersedes every
delta published against earlier generations, so ``compact(active_gen)``
drops all segments older than the active generation (the swapper calls it
after ``activate`` when it owns the log).

**Fsync policy** (``fsync=``): ``"always"`` fsyncs every append — a
publish that returned is on disk; ``"rotate"`` fsyncs only at segment
rotation, explicit ``sync()``, and ``close()`` — a crash can lose the
tail of the active segment but never re-orders or corrupts it;
``"never"`` leaves flushing to the OS (benchmark floor).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import struct
import threading
import time
import zlib
from typing import IO, Callable, Iterator, List, Optional, Tuple

from photon_ml_tpu.chaos.injector import fault as _chaos_fault
from photon_ml_tpu.obs.registry import MetricsRegistry

logger = logging.getLogger("photon_ml_tpu.online.delta_log")

_MAGIC = b"PHOTDLG1"
_HEADER = struct.Struct("<II")  # payload length, crc32(payload)
# a length field past this is framing garbage, not a record — refuse to
# allocate for it even if the file claims to be that long
_MAX_PAYLOAD = 1 << 30
_FSYNC_POLICIES = ("always", "rotate", "never")


@dataclasses.dataclass(frozen=True)
class DeltaRecord:
    """One published coefficient-row update, identity included."""

    generation: int
    delta_version: int
    cid: str
    entity: str
    row: Tuple[float, ...]

    @property
    def identity(self) -> Tuple[int, int]:
        return (self.generation, self.delta_version)

    def encode(self) -> bytes:
        payload = json.dumps(
            {"g": self.generation, "v": self.delta_version, "c": self.cid,
             "e": self.entity, "r": list(self.row)},
            separators=(",", ":")).encode("utf-8")
        return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload

    @classmethod
    def decode_payload(cls, payload: bytes) -> "DeltaRecord":
        obj = json.loads(payload.decode("utf-8"))
        return cls(generation=int(obj["g"]), delta_version=int(obj["v"]),
                   cid=str(obj["c"]), entity=str(obj["e"]),
                   row=tuple(float(x) for x in obj["r"]))


def _segment_name(generation: int) -> str:
    return f"segment-{generation:010d}.log"


def _scan_segment(path: str) -> Tuple[List[DeltaRecord], int]:
    """All valid records in a segment plus the byte length of the valid
    prefix.  Framing violations end the scan (torn tail) — never raise."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        logger.warning("delta log: unreadable segment %s: %s", path, e)
        return [], 0
    if len(data) < len(_MAGIC) or data[: len(_MAGIC)] != _MAGIC:
        logger.warning("delta log: segment %s missing magic header", path)
        return [], 0
    records: List[DeltaRecord] = []
    pos = len(_MAGIC)
    while True:
        if pos + _HEADER.size > len(data):
            break  # torn/absent header
        length, crc = _HEADER.unpack_from(data, pos)
        end = pos + _HEADER.size + length
        if length > _MAX_PAYLOAD or end > len(data):
            break  # torn payload (or garbage length)
        payload = data[pos + _HEADER.size: end]
        if zlib.crc32(payload) != crc:
            break  # corrupt record: treat as torn tail
        try:
            records.append(DeltaRecord.decode_payload(payload))
        except (ValueError, KeyError, TypeError):
            break  # CRC-valid but undecodable — still never raise
        pos = end
    return records, pos


class DeltaLog:
    """Append/replay/compact over one log directory (module docstring).

    Thread-safe for one writer process: ``append`` serializes under a lock
    (the swapper's own ``_swap_lock`` already orders publishes; this lock
    keeps the log safe if ``sync``/``compact`` race an append).  Readers
    in OTHER processes replay concurrently without coordination — they
    only ever see a prefix of committed records plus at most one torn
    tail, which replay ignores.
    """

    def __init__(self, path: str, fsync: str = "always",
                 registry: Optional[MetricsRegistry] = None):
        if fsync not in _FSYNC_POLICIES:
            raise ValueError(
                f"fsync must be one of {_FSYNC_POLICIES}, got {fsync!r}")
        self.path = path
        self.fsync = fsync
        self._registry = registry
        self._lock = threading.Lock()
        self._file: Optional[IO[bytes]] = None
        self._file_generation: Optional[int] = None
        os.makedirs(path, exist_ok=True)
        self._last: Optional[Tuple[int, int]] = self.last_identity()
        self.bytes_written = 0
        self.records_written = 0
        # Degradation state (chaos/health consume these): ``healthy``
        # flips False on an append write error and True again on the next
        # successful append — the disk healed.  The log NEVER takes the
        # process down; publishes fail loudly while serving continues.
        self.healthy = True
        self.write_errors = 0
        self._listeners: List[Callable[[DeltaRecord], None]] = []
        # Optional retention floor provider (photonrepl installs one): a
        # callable returning the lowest generation that must survive
        # compaction, or None when nothing pins the log.
        self.retention_pin: Optional[Callable[[], Optional[int]]] = None

    # -- listeners ---------------------------------------------------------
    def add_listener(self, fn: Callable[[DeltaRecord], None]) -> None:
        """Register a callback fired after each durable append, outside the
        log lock and in append order (single-writer log).  Listener
        exceptions are swallowed — fan-out must never poison the publish
        path."""
        self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[DeltaRecord], None]) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    # -- inspection --------------------------------------------------------
    def segments(self) -> List[Tuple[int, str]]:
        """(generation, path) for every segment on disk, ascending."""
        out = []
        for name in os.listdir(self.path):
            if name.startswith("segment-") and name.endswith(".log"):
                try:
                    gen = int(name[len("segment-"): -len(".log")])
                except ValueError:
                    continue
                out.append((gen, os.path.join(self.path, name)))
        return sorted(out)

    def min_retained_generation(self) -> Optional[int]:
        """Oldest generation still on disk, or None for an empty log."""
        segs = self.segments()
        return segs[0][0] if segs else None

    def last_identity(self) -> Optional[Tuple[int, int]]:
        """Identity of the newest valid record, or None for an empty log.
        Scans segments newest-first so a header-only segment falls through
        to the previous one."""
        for gen, path in reversed(self.segments()):
            records, _ = _scan_segment(path)
            if records:
                return records[-1].identity
        return None

    # -- writing -----------------------------------------------------------
    def append(self, record: DeltaRecord) -> None:
        """Durably append one record; identities must be strictly
        increasing (a regression is a writer bug — raise, don't corrupt
        every replica downstream)."""
        with self._lock:
            if self._last is not None and record.identity <= self._last:
                raise ValueError(
                    f"delta log: non-monotone identity {record.identity} "
                    f"after {self._last} — writer restart without "
                    "advance_generation_floor, or two writers on one log")
            frame = record.encode()
            try:
                f = self._segment_for(record.generation)
                # valid-frame boundary BEFORE the write: "ab" mode means
                # writes always land at EOF, but truncate() still works —
                # this offset is what a failed append rolls back to
                pos = f.seek(0, os.SEEK_END)
            except OSError:
                self._note_write_error()
                raise
            try:
                act = _chaos_fault("delta_log.append")
                if act is not None:
                    if act.kind == "torn":
                        # commit a partial frame first so recovery has a
                        # REAL torn tail to truncate, not a clean boundary
                        f.write(frame[:max(1, len(frame) // 2)])
                        f.flush()
                    raise act.to_error()
                f.write(frame)
                f.flush()
                if self.fsync == "always":
                    act = _chaos_fault("delta_log.fsync")
                    if act is not None:
                        raise act.to_error()
                    self._fsync(f)
            except OSError:
                self._note_write_error()
                self._truncate_to(f, pos)
                raise
            self.healthy = True
            self._last = record.identity
            self.bytes_written += len(frame)
            self.records_written += 1
        if self._registry is not None:
            self._registry.inc("delta_log_bytes_total", len(frame))
            self._registry.inc("delta_log_records_total")
        for fn in list(self._listeners):
            try:
                fn(record)
            except Exception:  # noqa: BLE001 — see add_listener contract
                logger.exception("delta log: append listener failed")

    def _note_write_error(self) -> None:
        # only reached from append's `with self._lock` block
        self.write_errors += 1
        self.healthy = False  # photonlint: disable=lock-discipline -- caller holds self._lock
        if self._registry is not None:
            self._registry.inc("delta_log_write_errors_total")

    def _truncate_to(self, f: IO[bytes], pos: int) -> None:
        """A write failed mid-frame: the segment must stay appendable.
        Roll back to the last valid frame boundary so the NEXT append
        lands on clean bytes instead of extending a torn frame that
        replay would stop at forever."""
        try:
            f.truncate(pos)
        except OSError:
            # disk too sick even to truncate: drop the handle — the next
            # append reopens via _segment_for, whose torn-tail scan
            # repairs the file from disk state
            logger.exception(
                "delta log: truncate after failed append failed; closing "
                "segment handle for reopen-repair")
            try:
                f.close()
            except OSError:
                pass
            self._file = None
            self._file_generation = None

    def _segment_for(self, generation: int) -> IO[bytes]:
        if self._file is not None and self._file_generation == generation:
            return self._file
        self._close_current()
        path = os.path.join(self.path, _segment_name(generation))
        if os.path.exists(path):
            # crash recovery: never append after a torn tail — replay stops
            # at the tear, so records beyond it would be invisible forever
            _, valid_len = _scan_segment(path)
            size = os.path.getsize(path)
            if valid_len < size:
                logger.warning(
                    "delta log: truncating torn tail of %s (%d -> %d bytes)",
                    path, size, valid_len)
                with open(path, "r+b") as f:
                    f.truncate(valid_len)
            self._file = open(path, "ab")
        else:
            self._file = open(path, "ab")
            self._file.write(_MAGIC)
            self._file.flush()
            if self.fsync != "never":
                self._fsync(self._file)
        self._file_generation = generation
        return self._file

    def _fsync(self, f: IO[bytes]) -> None:
        t0 = time.perf_counter()
        os.fsync(f.fileno())
        if self._registry is not None:
            self._registry.observe("delta_log_fsync_s",
                                   time.perf_counter() - t0)

    def _close_current(self) -> None:
        if self._file is not None:
            self._file.flush()
            if self.fsync != "never":
                self._fsync(self._file)
            self._file.close()
            self._file = None
            self._file_generation = None

    def sync(self) -> None:
        """Force the active segment to disk (no-op under ``always``)."""
        with self._lock:
            if self._file is not None:
                self._file.flush()
                self._fsync(self._file)

    def close(self) -> None:
        with self._lock:
            self._close_current()

    # -- reading -----------------------------------------------------------
    def replay(self, after: Optional[Tuple[int, int]] = None,
               ) -> Iterator[DeltaRecord]:
        """Every committed record in identity order, skipping identities at
        or below ``after``.  Torn tails are ignored; never raises."""
        for gen, path in self.segments():
            if after is not None and gen < after[0]:
                continue
            records, _ = _scan_segment(path)
            for r in records:
                if after is not None and r.identity <= after:
                    continue
                yield r

    # -- compaction --------------------------------------------------------
    def compact(self, active_generation: int) -> List[int]:
        """Drop segments older than the active generation (their deltas are
        baked into — or superseded by — the active snapshot).  When a
        ``retention_pin`` provider is installed (photonrepl: minimum
        acknowledged follower generation), segments at or above the pinned
        generation survive even if the owner has moved past them, so slow
        followers can still resume via log replay.  Returns the dropped
        generations."""
        floor = active_generation
        if self.retention_pin is not None:
            try:
                pin = self.retention_pin()
            except Exception:  # noqa: BLE001 — pin must not block compaction
                logger.exception("delta log: retention pin provider failed")
                pin = None
            if pin is not None and pin < floor:
                floor = pin
                logger.info(
                    "delta log: compaction floor pinned at gen %d "
                    "(active gen %d) by a connected follower",
                    floor, active_generation)
        dropped = []
        with self._lock:
            for gen, path in self.segments():
                if gen >= floor:
                    continue
                if self._file_generation == gen:
                    self._close_current()
                try:
                    os.remove(path)
                    dropped.append(gen)
                except OSError as e:
                    logger.warning("delta log: compact could not drop %s: %s",
                                   path, e)
        if dropped and self._registry is not None:
            self._registry.inc("delta_log_segments_compacted_total",
                               len(dropped))
        if dropped:
            logger.info("delta log: compacted %d segment(s) older than gen "
                        "%d", len(dropped), floor)
        return dropped
