"""Device-resident, versioned entity coefficient store.

Photon ML reference counterpart: the PalDB off-heap key-value stores LinkedIn
publishes GLMix models into for online serving (one store per random-effect
coordinate, entity id -> sparse coefficient vector; see PAPER.md §"model
deployment"), plus the broadcast fixed-effect coefficients.  TPU-native
shape: the per-coordinate "KV store" is a dense ``jnp`` table
``[hot_entities, d]`` resident in device memory, indexed by slot through the
same entity-id machinery training uses (``data/reader.EntityIndex`` for
string id -> int, ``game/coordinate._slots_from`` semantics for id -> row),
so scoring a micro-batch is one gather instead of per-request KV lookups.

Entities beyond the device budget ("cold" — the long tail of a
millions-of-entities random effect) stay host-side and are resolved through
an LRU-fronted fallback: their coefficient rows are gathered per batch into
a tiny overflow buffer that the engine scores with the same contraction the
device table uses, so hot and cold entities produce bitwise-identical
scores.  Unknown entities score 0, exactly like the batch path
(RandomEffectModel.score missing-entity convention).

Stores are immutable and versioned: hot swap (serving/swap.py) builds a new
store from a new model directory and flips the engine's generation pointer;
in-flight requests keep scoring against the store they started with.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.models.game import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.types import TaskType

Array = jax.Array

_generation = itertools.count(1)


@dataclasses.dataclass(frozen=True)
class StoreConfig:
    """Build-time knobs, carried on the store so hot swap rebuilds the next
    version with identical policy (serving/swap.py).

    ``device_capacity``: max entity rows resident on device per coordinate
    (None = all — the small-model default).  Hot entities are the FIRST rows
    of the training-order stack; a frequency-ranked hot set is a follow-on
    (ROADMAP).  ``lru_capacity``: host-side LRU entries per coordinate for
    cold rows.  ``x_dtype``: request feature dtype (float32, matching
    data/reader's default design dtype — part of the bitwise-parity
    contract with batch scoring)."""

    device_capacity: Optional[int] = None
    lru_capacity: int = 4096
    x_dtype: np.dtype = np.float32


class ColdEntityCache:
    """LRU front for cold-entity coefficient rows.

    ``fetch_row`` abstracts the backing archive (here: the host copy of the
    model's coefficient stack; in a production deployment: mmap/disk — the
    PalDB page-cache analog).  The LRU makes repeat lookups of a recently
    seen cold entity O(1) without re-touching the archive."""

    def __init__(self, fetch_row: Callable[[int], Optional[np.ndarray]],
                 capacity: int, metrics: Optional[ServingMetrics] = None):
        self._fetch = fetch_row
        self._capacity = max(1, capacity)
        self._lru: "OrderedDict[int, np.ndarray]" = OrderedDict()
        self._metrics = metrics
        # resolve() runs on whatever thread scores the batch; concurrent
        # scorers share this cache, and OrderedDict corrupts under
        # unsynchronized move_to_end/popitem
        self._lock = threading.Lock()

    def get(self, entity_id: int) -> Optional[np.ndarray]:
        with self._lock:
            row = self._lru.get(entity_id)
            if row is not None:
                self._lru.move_to_end(entity_id)
                if self._metrics is not None:
                    self._metrics.inc("lru_hits")
                return row
        row = self._fetch(entity_id)
        if row is None:
            return None
        if self._metrics is not None:
            self._metrics.inc("cold_fetches")
        with self._lock:
            self._lru[entity_id] = row
            if len(self._lru) > self._capacity:
                self._lru.popitem(last=False)
                if self._metrics is not None:
                    self._metrics.inc("lru_evictions")
        return row


@dataclasses.dataclass
class FixedCoordinate:
    """Broadcast fixed-effect weights (reference FixedEffectModel broadcast)."""

    cid: str
    feature_shard: str
    weights: Array  # [d], device-resident


@dataclasses.dataclass
class RandomCoordinate:
    """One random-effect coordinate's device table + host fallback."""

    cid: str
    feature_shard: str
    random_effect_type: str
    table: Array              # [hot, d] device-resident hot rows
    dim: int
    hot_slot_of: Dict[int, int]   # entity id -> device row (slot < hot)
    cold: ColdEntityCache         # entity id -> host row for slot >= hot
    num_entities: int             # hot + cold

    @property
    def hot_entities(self) -> int:
        return self.table.shape[0]


class CoefficientStore:
    """One immutable model version, device-ready (see module docstring)."""

    def __init__(self, task: TaskType,
                 coordinates: Dict[str, Union[FixedCoordinate,
                                              RandomCoordinate]],
                 entity_indexes: Dict[str, EntityIndex],
                 index_maps: Dict[str, "IndexMap"],
                 shard_dims: Dict[str, int],
                 config: StoreConfig,
                 version: str = ""):
        self.task = task
        self.coordinates = coordinates
        self.order: List[str] = list(coordinates)  # additive-score order
        self.entity_indexes = entity_indexes
        self.index_maps = index_maps
        self.shard_dims = shard_dims
        self.config = config
        self.version = version
        self.generation = next(_generation)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_bundle(cls, bundle, config: Optional[StoreConfig] = None,
                    version: str = "",
                    metrics: Optional[ServingMetrics] = None,
                    ) -> "CoefficientStore":
        """Build from a storage/model_io.ModelBundle (the load_model_bundle
        result) — the path both cold start (cli/serve.py) and hot swap
        (serving/swap.py) share."""
        return cls.from_model(bundle.model, bundle.task,
                              bundle.entity_indexes, bundle.index_maps,
                              config=config,
                              version=version or bundle.model_dir,
                              metrics=metrics)

    @classmethod
    def from_model(cls, model: GameModel, task: TaskType,
                   entity_indexes: Dict[str, EntityIndex],
                   index_maps: Dict[str, "IndexMap"],
                   config: Optional[StoreConfig] = None,
                   version: str = "",
                   metrics: Optional[ServingMetrics] = None,
                   ) -> "CoefficientStore":
        config = config or StoreConfig()
        coordinates: Dict[str, Union[FixedCoordinate, RandomCoordinate]] = {}
        shard_dims: Dict[str, int] = {}

        def _shard_dim(shard: str, d: int, cid: str) -> None:
            have = shard_dims.setdefault(shard, d)
            if have != d:
                raise ValueError(
                    f"coordinate {cid!r}: shard {shard!r} width {d} "
                    f"conflicts with another coordinate's {have}")

        for cid, m in model.models.items():
            if isinstance(m, FixedEffectModel):
                w = np.asarray(m.coefficients.means)
                _shard_dim(m.feature_shard, w.shape[-1], cid)
                coordinates[cid] = FixedCoordinate(
                    cid=cid, feature_shard=m.feature_shard,
                    weights=jnp.asarray(w))
            elif isinstance(m, RandomEffectModel):
                w_stack = np.asarray(m.w_stack)
                n_ent, d = w_stack.shape
                _shard_dim(m.feature_shard, d, cid)
                hot = n_ent if config.device_capacity is None else min(
                    config.device_capacity, n_ent)
                # device table = the first `hot` stack rows; colder rows stay
                # host-side behind the LRU (full stack kept as the archive —
                # host RAM is the PalDB store, device HBM holds the hot set).
                # The table keeps at least one row: score_samples clamps
                # missing slots to row 0, which must exist to gather from
                # (an all-cold or entity-less coordinate serves a zero row).
                if hot < 1:
                    hot = 0
                    table = jnp.zeros((1, d), w_stack.dtype)
                else:
                    table = jnp.asarray(w_stack[:hot] if hot < n_ent
                                        else w_stack)
                hot_slot_of = {eid: s for eid, s in m.slot_of.items()
                               if s < hot}
                cold_slot_of = {eid: s for eid, s in m.slot_of.items()
                                if s >= hot}

                def _fetch(eid: int, _stack=w_stack, _cold=cold_slot_of
                           ) -> Optional[np.ndarray]:
                    slot = _cold.get(eid)
                    return None if slot is None else _stack[slot]

                coordinates[cid] = RandomCoordinate(
                    cid=cid, feature_shard=m.feature_shard,
                    random_effect_type=m.random_effect_type,
                    table=table, dim=d, hot_slot_of=hot_slot_of,
                    cold=ColdEntityCache(_fetch, config.lru_capacity,
                                         metrics),
                    num_entities=n_ent)
            else:
                raise ValueError(
                    f"coordinate {cid!r}: serving supports FixedEffectModel "
                    f"and dense RandomEffectModel (got {type(m).__name__}); "
                    "convert compact models with .to_dense(), or see "
                    "ROADMAP's sparse-serving follow-on")
        for shard, d in shard_dims.items():
            imap = index_maps.get(shard)
            if imap is None:
                raise ValueError(
                    f"feature shard {shard!r} has no index map — requests "
                    "cannot be densified without it")
            if imap.size != d:
                raise ValueError(
                    f"feature shard {shard!r}: index map has {imap.size} "
                    f"features but the model expects {d} — wrong index map "
                    "for this model version")
        return cls(task=task, coordinates=coordinates,
                   entity_indexes=entity_indexes, index_maps=index_maps,
                   shard_dims=shard_dims, config=config, version=version)

    # -- shape signature (compiled-executable cache key) -------------------
    def signature(self) -> Tuple:
        """Everything that determines compiled-kernel shapes/dtypes.  Two
        model versions with an equal signature share AOT executables, which
        is what makes same-shape hot swaps recompile-free."""
        parts = []
        for cid in self.order:
            c = self.coordinates[cid]
            if isinstance(c, FixedCoordinate):
                parts.append(("fixed", cid, c.feature_shard,
                              c.weights.shape, str(c.weights.dtype)))
            else:
                parts.append(("random", cid, c.feature_shard,
                              c.table.shape, str(c.table.dtype)))
        return (tuple(parts), tuple(sorted(self.shard_dims.items())),
                str(np.dtype(self.config.x_dtype)))

    # -- lookups -----------------------------------------------------------
    def entity_id(self, re_type: str, name: Optional[str]) -> int:
        """Entity string -> trained int id; -1 when unknown.  READ-ONLY:
        serving must never grow the training-time index."""
        if name is None:
            return -1
        eidx = self.entity_indexes.get(re_type)
        return -1 if eidx is None else eidx.get(str(name))

    def resolve(self, cid: str, entity_names: Sequence[Optional[str]],
                metrics: Optional[ServingMetrics] = None
                ) -> Tuple[np.ndarray, np.ndarray]:
        """Per-sample (device slots, cold overflow rows) for one coordinate.

        ``slots[i]``: device-table row of sample i's entity, or -1 (cold or
        unknown — the device kernel scores those 0, the reference's missing-
        entity convention).  ``overflow[i]``: the cold entity's host
        coefficient row (zeros for hot/unknown samples); the engine adds
        ``einsum('nd,nd->n', x, overflow)`` so a cold entity scores exactly
        as if its row were in the device table."""
        c = self.coordinates[cid]
        n = len(entity_names)
        slots = np.full(n, -1, np.int32)
        overflow = np.zeros((n, c.dim), c.table.dtype)
        misses = 0
        for i, name in enumerate(entity_names):
            eid = self.entity_id(c.random_effect_type, name)
            if eid < 0:
                misses += 1
                continue
            slot = c.hot_slot_of.get(eid)
            if slot is not None:
                slots[i] = slot
                continue
            row = c.cold.get(eid)
            if row is None:
                misses += 1
            else:
                overflow[i] = row
        if metrics is not None and misses:
            metrics.inc("entity_misses", misses)
        return slots, overflow
