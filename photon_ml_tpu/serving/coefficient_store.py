"""Device-resident, versioned entity coefficient store.

Photon ML reference counterpart: the PalDB off-heap key-value stores LinkedIn
publishes GLMix models into for online serving (one store per random-effect
coordinate, entity id -> sparse coefficient vector; see PAPER.md §"model
deployment"), plus the broadcast fixed-effect coefficients.  TPU-native
shape: the per-coordinate "KV store" is a dense ``jnp`` table
``[hot_entities, d]`` resident in device memory, indexed by slot through the
same entity-id machinery training uses (``data/reader.EntityIndex`` for
string id -> int, ``game/coordinate._slots_from`` semantics for id -> row),
so scoring a micro-batch is one gather instead of per-request KV lookups.

Entities beyond the device budget ("cold" — the long tail of a
millions-of-entities random effect) stay host-side and are resolved through
an LRU-fronted fallback: their coefficient rows are gathered per batch into
a tiny overflow buffer that the engine scores with the same contraction the
device table uses, so hot and cold entities produce bitwise-identical
scores.  Unknown entities score 0, exactly like the batch path
(RandomEffectModel.score missing-entity convention).

Device residency is **frequency-ranked**: every resolve records per-entity
hits, and a promotion/demotion pass (``CoefficientStore.rebalance``, driven
periodically by ``HotSetManager``) scatters the hottest rows from the host
archive into the device table and evicts the coldest — so under skewed
(zipf) traffic the device table tracks actual load instead of
training-slot order.  Hit counters decay exponentially per pass (EWMA), so
yesterday's hot entities age out.

When the store is sharded over a serving mesh, placement is **traffic
aware**: every sharded coordinate carries an explicit entity->shard routing
table (``_route``, indexed by archive slot) that starts as the round-robin
``slot % n_shards`` layout and is re-fit at each ``rebalance()`` by a
deterministic greedy bin-pack on the EWMA load — the hottest entities are
placed first, an incumbent keeps its shard unless that shard's predicted
load exceeds the lightest shard's by a slack factor (hysteresis: a near-tie
never churns routing), and overflow spills to the least-loaded shard.  The
top-``replicate_top_k`` zipf-head entities additionally get hot residency
on EVERY shard (``HotSet.replicas``): reads stay shard-local (resolve
spreads a batch's lookups across the replica rows), writes stay coherent
because ``apply_delta`` scatters one payload to every replica row in one
launch under the same (generation, delta_version) identity, and rebalance
promotes/demotes replica rows like any other row.  None of this changes a
table SHAPE or the store ``signature()``, so the zero-recompile contract is
untouched — the mesh kernels localize GLOBAL rows and non-owning shards
contribute exactly zero to the margin psum, which is also why scores are
bitwise identical under ANY routing/replication choice.

Stores are versioned: hot swap (serving/swap.py) builds a new store from a
new model directory and flips the engine's generation pointer; in-flight
requests keep scoring against the store they started with.  Within one
generation exactly two things mutate, both under per-coordinate locks with
the (table, slot map) pair swapped as ONE immutable snapshot so readers
never see a torn hot set: the rebalance pass above, and **streaming
deltas** (``apply_delta`` — scatter one online-learned coefficient row into
the live table without a generation flip; serving/swap.py counts them into
``delta_version``).  Neither ever changes a table's SHAPE, so every AOT
executable compiled against the generation stays valid — the engine's
zero-recompile guarantee survives both.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import (Callable, Dict, List, NamedTuple, Optional, Sequence,
                    Tuple, Union)
from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.models.game import (CompactRandomEffectModel,
                                       FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.obs.trace import span as obs_span
from photon_ml_tpu.parallel.mesh import SHARD_AXIS, serving_mesh
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.types import TaskType

Array = jax.Array

class _GenerationCounter:
    """Monotone process-wide generation source with a raisable floor.

    ``next()`` semantics match the ``itertools.count`` it replaces; the
    floor exists for delta-log writers (online/delta_log.py): a restarted
    trainer process would otherwise mint generation 1 again and append
    records whose ``(generation, delta_version)`` identity collides with —
    or sorts below — what the log already holds, breaking the log's
    monotone-identity contract for every replica following it."""

    def __init__(self, start: int = 1):
        self._next = start
        self._lock = threading.Lock()

    def __next__(self) -> int:
        with self._lock:
            n = self._next
            self._next += 1
            return n

    def advance_to(self, floor: int) -> None:
        with self._lock:
            self._next = max(self._next, floor)


_generation = _GenerationCounter()


def advance_generation_floor(floor: int) -> None:
    """Ensure every generation minted from now on is >= ``floor``."""
    _generation.advance_to(int(floor))

# frequencies at or below this after decay are zeroed in the counter table —
# the long tail of one-hit entities must not keep rows in the ranked set
_FREQ_FLOOR = 1e-3

# routing hysteresis: an incumbent keeps its shard while that shard's
# predicted load stays within this factor of the lightest shard's (plus the
# entity's own load, so the first placements are always incumbent-kept) —
# uniform traffic therefore never reroutes, while a zipf head whose home
# shard carries a multiple of the lightest one spills deterministically
_ROUTE_SLACK = 1.25


@dataclasses.dataclass(frozen=True)
class StoreConfig:
    """Build-time knobs, carried on the store so hot swap rebuilds the next
    version with identical policy (serving/swap.py).

    ``device_capacity``: max entity rows resident on device per coordinate
    (None = all — the small-model default).  The initial hot set is the
    first ``device_capacity`` training slots; ``rebalance()`` then re-ranks
    residency by observed request frequency.  ``lru_capacity``: host-side
    LRU entries per coordinate for cold rows.  ``hot_decay``: multiplier
    applied to every entity hit counter at each rebalance pass (EWMA — 0.5
    halves an idle entity's rank per pass).  ``hot_max_moves``: cap on
    promotions per coordinate per pass (None = unlimited) so one pass never
    stalls the scoring threads behind a giant scatter.
    ``hot_tracked_max``: cap on entities carrying a nonzero hit counter
    between passes (None = unlimited) — at each rebalance the counter table
    is pruned to the top ``hot_tracked_max`` by an ``argpartition`` pass,
    bounding the ranked candidate set at millions of entities.
    ``x_dtype``: request feature dtype (float32, matching data/reader's
    default design dtype — part of the bitwise-parity contract with batch
    scoring).
    ``mesh_shards``: partition every random-effect table's entity axis
    over the first ``mesh_shards`` devices (``parallel/mesh.serving_mesh``,
    axis ``shard``).  0 = unsharded (the single-device layout).  When
    sharded, ``device_capacity`` is the hot-row budget PER SHARD — one
    chip's HBM share — so aggregate hot capacity scales with the mesh
    (``mesh_shards * device_capacity`` rows per coordinate), which is the
    entire point of pod-slice serving.  A 1-shard mesh serves bitwise the
    unsharded scores.  ``hot_max_moves`` applies per shard per pass.
    ``fleet_axis``: the MODEL axis of the executable-cache key
    (serving/fleet).  Every store on the default axis (``""``) with equal
    shapes shares AOT executables — N same-shape tenant models on one
    ``KernelCache`` compile once; distinct-shape models coexist because the
    shapes themselves are in the signature.  A tenant that must not share
    compiled programs (e.g. a private donation/layout policy) registers
    under its own axis value, which forces coexistence without sharing.
    ``load_aware_routing``: re-fit the entity->shard routing table at each
    rebalance by the greedy load bin-pack (module docstring).  ``False``
    freezes routing at the round-robin ``slot % n_shards`` layout — the
    pre-traffic-aware router, kept for A/B curves and as the escape hatch.
    ``replicate_top_k``: give the top-k hottest entities (by EWMA load) hot
    residency on every shard (0 = replication off).  Both are placement
    policy only: no shape, no signature, no score bit changes."""

    device_capacity: Optional[int] = None
    lru_capacity: int = 4096
    hot_decay: float = 0.5
    hot_max_moves: Optional[int] = None
    hot_tracked_max: Optional[int] = None
    x_dtype: np.dtype = np.float32
    mesh_shards: int = 0
    fleet_axis: str = ""
    load_aware_routing: bool = True
    replicate_top_k: int = 0


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """One coordinate's entity-axis partition over the serving mesh.

    The device table is ONE logical array of ``n_shards * cap`` rows whose
    leading axis is laid out ``NamedSharding(mesh, P(SHARD_AXIS))`` — shard
    ``s`` physically owns global rows ``[s*cap, (s+1)*cap)``.  Entities
    START routed round-robin by archive slot (``archive_slot % n_shards``
    — ``shard_of_archive_slot``, the default/fallback router), which
    balances shard population to within one row and makes the 1-shard case
    collapse to exactly the unsharded layout; the LIVE assignment is the
    coordinate's traffic-aware routing table (``RandomCoordinate
    .shard_of_slots``), which rebalance re-fits to observed load.
    ``slot_of`` values stay GLOBAL rows, so ``resolve`` and every
    snapshot/scatter path are layout-agnostic; only the engine's kernel
    decomposes slot -> (shard, local row), and rebalance places residency
    into each shard's own rows."""

    mesh: Mesh
    n_shards: int
    cap: int  # hot rows per shard (>= 0; 0 = all-cold coordinate)

    @property
    def sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(SHARD_AXIS))

    def shard_of_archive_slot(self, archive_slots):
        """Which shard serves an entity, from its archive slot (vectorized)."""
        return archive_slots % self.n_shards


class ColdEntityCache:
    """LRU front for cold-entity coefficient rows.

    ``fetch_row`` abstracts the backing archive (here: the host copy of the
    model's coefficient stack; in a production deployment: mmap/disk — the
    PalDB page-cache analog).  The LRU makes repeat lookups of a recently
    seen cold entity O(1) without re-touching the archive."""

    def __init__(self, fetch_row: Callable[[int], Optional[np.ndarray]],
                 capacity: int, metrics: Optional[ServingMetrics] = None):
        self._fetch = fetch_row
        self._capacity = max(1, capacity)
        self._lru: "OrderedDict[int, np.ndarray]" = OrderedDict()
        self._metrics = metrics
        # resolve() runs on whatever thread scores the batch; concurrent
        # scorers share this cache, and OrderedDict corrupts under
        # unsynchronized move_to_end/popitem
        self._lock = threading.Lock()

    def get(self, entity_id: int) -> Optional[np.ndarray]:
        with self._lock:
            row = self._lru.get(entity_id)
            if row is not None:
                self._lru.move_to_end(entity_id)
                if self._metrics is not None:
                    self._metrics.inc("lru_hits")
                return row
        row = self._fetch(entity_id)
        if row is None:
            return None
        if self._metrics is not None:
            self._metrics.inc("cold_fetches")
        with self._lock:
            self._lru[entity_id] = row
            if len(self._lru) > self._capacity:
                self._lru.popitem(last=False)
                if self._metrics is not None:
                    self._metrics.inc("lru_evictions")
        return row

    def invalidate(self, entity_id: int) -> None:
        """Drop one entry (stale after a streaming delta rewrote its row, or
        redundant after the entity was promoted onto the device)."""
        with self._lock:
            self._lru.pop(entity_id, None)


@dataclasses.dataclass
class FixedCoordinate:
    """Broadcast fixed-effect weights (reference FixedEffectModel broadcast)."""

    cid: str
    feature_shard: str
    weights: Array  # [d], device-resident


class HotSet(NamedTuple):
    """One consistent device-residency snapshot: gather table + the entity
    id -> device-row map that indexes it.  Replaced atomically as a unit —
    a resolve that grabbed this snapshot can never pair stale slots with a
    rebalanced table.  ``replicas`` lists EVERY device row holding a
    replicated zipf-head entity (``slot_of`` keeps its primary row);
    entities absent from it live on exactly one row.  Never mutated in
    place — rebalance publishes a whole new dict with the snapshot."""

    table: Array            # [max(capacity, 1), d] device-resident rows
    slot_of: Dict[int, int]  # entity id -> primary device row
    replicas: Dict[int, Tuple[int, ...]] = {}  # eid -> all rows (hot heads)


class CompactHotSet(NamedTuple):
    """The sparse twin: one consistent (indices, values, slot map) triple.
    ``indices[row]`` are that entity's observed column ids (``dim``-padded,
    ascending — CompactRandomEffectModel's row layout verbatim), ``values``
    align.  Replaced atomically as ONE object, same contract as HotSet
    (including the ``replicas`` map for replicated zipf-head rows)."""

    indices: Array           # [max(capacity, 1), k] int32 device rows
    values: Array            # [max(capacity, 1), k] device rows
    slot_of: Dict[int, int]  # entity id -> primary device row
    replicas: Dict[int, Tuple[int, ...]] = {}  # eid -> all rows (hot heads)


class RandomCoordinate:
    """One random-effect coordinate: device hot set, host archive, LRU.

    ``archive`` is the full host-side coefficient stack (the PalDB store
    analog); the device table holds the ``hot_capacity`` rows serving
    residency currently favors.  Residency starts as the first
    ``hot_capacity`` training slots and is re-ranked by ``rebalance()``
    from the EWMA hit counters ``record_hits`` accumulates.  The counters
    live in an ARRAY-BACKED table (``_freq[eid]``), so the hot-path fold is
    one vectorized scatter-add and the ranking pass is numpy
    (``lexsort``/``argpartition``) instead of a Python ``sorted`` over a
    dict — the GIL-bound pass the ROADMAP flagged at millions of tracked
    entities.  All mutation — counters, promotion/demotion, streaming
    deltas — happens under ``self._lock``; readers take the ``hot``
    snapshot once and are consistent without locking.

    The row REPRESENTATION (dense [d] vectors here, compact (indices,
    values) pairs in ``CompactRandomCoordinate``) is isolated behind five
    small hooks — ``_initial_hot``, ``_archive_rows``, ``_scatter_rows``,
    ``_delta_payload``, ``_write_archive_row`` — so the frequency ranking,
    hysteresis, move caps and snapshot swap discipline are ONE
    implementation for both layouts.
    """

    kind = "dense"

    def __init__(self, cid: str, feature_shard: str, random_effect_type: str,
                 archive: np.ndarray, archive_slot_of: Dict[int, int],
                 hot_capacity: int, lru_capacity: int,
                 metrics: Optional[ServingMetrics] = None,
                 decay: float = 0.5,
                 max_moves: Optional[int] = None,
                 tracked_max: Optional[int] = None,
                 shard_spec: Optional[ShardSpec] = None,
                 load_aware: bool = True,
                 replicate_top_k: int = 0):
        self.cid = cid
        self.feature_shard = feature_shard
        self.random_effect_type = random_effect_type
        self.shard_spec = shard_spec
        self._metrics = metrics
        self._bind_archive(archive)
        self.archive_slot_of = archive_slot_of  # entity id -> archive row
        self.hot_capacity = int(hot_capacity)
        self.decay = float(decay)
        self.max_moves = max_moves
        self.tracked_max = tracked_max
        self.load_aware = bool(load_aware)
        self.replicate_top_k = int(replicate_top_k)
        self._lock = threading.Lock()
        # array-backed frequency table + eid -> archive row as an array
        # (-1 = not this coordinate's entity); indexed by the dense entity
        # ids the EntityIndex hands out
        n_ids = (max(archive_slot_of) + 1) if archive_slot_of else 0
        self._slot_arr = np.full(n_ids, -1, np.int64)
        for eid, slot in archive_slot_of.items():
            self._slot_arr[eid] = slot
        self._freq = np.zeros(n_ids, np.float64)
        # traffic-aware routing table, archive slot -> serving shard.
        # Starts as the round-robin layout (exactly ShardSpec
        # .shard_of_archive_slot); rebalance replaces it WHOLESALE under
        # the lock, so readers (stats, admission) never see a torn table.
        if shard_spec is not None:
            self._route = (np.arange(max(self.num_entities, 1),
                                     dtype=np.int64)
                           % shard_spec.n_shards).astype(np.int32)
        else:
            self._route = None
        if self.hot_capacity < 1:
            # score_samples clamps missing slots to row 0, which must exist
            # to gather from — an all-cold coordinate serves a zero row
            slot_of: Dict[int, int] = {}
        elif shard_spec is None:
            slot_of = {eid: s for eid, s in archive_slot_of.items()
                       if s < self.hot_capacity}
        else:
            # round-robin routing: archive slot a lives on shard a % N at
            # initial local row a // N — for N=1 this is exactly the
            # unsharded first-capacity residency, row for row
            n, cap = shard_spec.n_shards, shard_spec.cap
            slot_of = {eid: (s % n) * cap + s // n
                       for eid, s in archive_slot_of.items() if s // n < cap}
        self._hot = self._initial_hot(slot_of)
        self.cold = ColdEntityCache(self._fetch_cold, lru_capacity, metrics)
        self._update_shard_gauges()

    # -- row-representation hooks (overridden by CompactRandomCoordinate) --
    def _bind_archive(self, archive: np.ndarray) -> None:
        self._archive = archive              # [n_ent, d] host rows
        self.num_entities, self.dim = archive.shape

    def _device_rows(self) -> int:
        """Rows of the device table: total hot capacity, or the guaranteed
        gather row — one per shard when sharded, one overall when not."""
        if self.shard_spec is not None:
            return max(self.hot_capacity, self.shard_spec.n_shards)
        return max(self.hot_capacity, 1)

    def _place(self, host_table: np.ndarray) -> Array:
        """Host table -> device, laid out over the serving mesh's shard
        axis when this coordinate is sharded."""
        if self.shard_spec is None:
            return jnp.asarray(host_table)
        return jax.device_put(jnp.asarray(host_table),
                              self.shard_spec.sharding)

    def _initial_hot(self, slot_of: Dict[int, int]) -> HotSet:
        rows = self._device_rows()
        table = np.zeros((rows, self.dim), self._archive.dtype)
        if slot_of:
            dev = np.fromiter(slot_of.values(), np.int64, len(slot_of))
            table[dev] = self._archive[self._slot_arr[
                np.fromiter(slot_of.keys(), np.int64, len(slot_of))]]
        return HotSet(self._place(table), slot_of)

    def _archive_rows(self, slots: np.ndarray):
        """Archive rows (whatever the representation) for a slot vector."""
        return self._archive[slots]

    def _scatter_rows(self, hot, dev_rows: List[int], payload,
                      slot_of: Dict[int, int], replicas=None):
        """New snapshot with ``payload`` scattered at ``dev_rows`` — ONE
        ``.at[rows].set`` launch per device array, shape unchanged."""
        rows = jnp.asarray(dev_rows, jnp.int32)
        return HotSet(self._repin(hot.table.at[rows].set(
            jnp.asarray(payload))), slot_of,
            hot.replicas if replicas is None else replicas)

    def _repin(self, table: Array) -> Array:
        """Keep the shard layout pinned across eager scatters.  XLA
        preserves the operand sharding for ``.at[rows].set`` today; the
        re-pin (a no-copy device_put when nothing changed) makes the AOT
        executables' layout contract independent of that inference."""
        if self.shard_spec is not None \
                and table.sharding != self.shard_spec.sharding:
            table = jax.device_put(table, self.shard_spec.sharding)
        return table

    def _delta_payload(self, row: np.ndarray):
        """Validate/convert one streaming-delta row into archive form."""
        row = np.asarray(row, dtype=self._archive.dtype)
        if row.shape != (self.dim,):
            raise ValueError(
                f"coordinate {self.cid!r}: delta row has shape {row.shape}, "
                f"expected ({self.dim},)")
        return row

    def _write_archive_row(self, slot: int, payload) -> None:
        self._archive[slot] = payload

    def _stack_rows(self, payloads: list):
        """Single rows -> the stacked form ``_scatter_rows`` consumes."""
        return np.stack(payloads)

    def _fetch_cold(self, eid: int):
        slot = self.archive_slot_of.get(eid)
        return None if slot is None else self._archive[slot]

    # -- reader surface ----------------------------------------------------
    @property
    def hot(self) -> HotSet:
        """The current residency snapshot (read once per resolve)."""
        # photonlint: disable=alias-escape -- documented snapshot-read
        # contract: the swap thread builds a NEW HotSet and replaces
        # self._hot under the lock; readers treat the handed-out set
        # as frozen (read once per resolve, never written)
        return self._hot

    @property
    def table(self) -> Array:
        # photonlint: disable=alias-escape -- same snapshot-read
        # contract as `hot`: the device table is replaced wholesale on
        # swap, and jax arrays are immutable to readers anyway
        return self._hot.table

    @property
    def hot_slot_of(self) -> Dict[int, int]:
        # photonlint: disable=alias-escape -- same snapshot-read
        # contract as `hot`: slot_of is built once per HotSet and
        # never updated in place after publication
        return self._hot.slot_of

    @property
    def hot_replicas(self) -> Dict[int, Tuple[int, ...]]:
        """eid -> EVERY device row holding the entity; entities resident
        on a single row are omitted (replicated zipf heads only)."""
        # photonlint: disable=alias-escape -- same snapshot-read
        # contract as `hot`: replicas is replaced wholesale on publish
        return self._hot.replicas

    # -- frequency tracking ------------------------------------------------
    def record_hits(self, counts: Dict[int, int]) -> None:
        """Fold one batch's per-entity hit counts into the EWMA counters —
        one vectorized scatter-add into the counter table.  Ids without an
        archive row (known to the entity index but never trained on this
        coordinate) are dropped: they can never be promoted."""
        if not counts:
            return
        eids = np.fromiter(counts.keys(), np.int64, len(counts))
        vals = np.fromiter(counts.values(), np.float64, len(counts))
        ok = (eids >= 0) & (eids < self._slot_arr.shape[0])
        eids, vals = eids[ok], vals[ok]
        ok = self._slot_arr[eids] >= 0
        eids, vals = eids[ok], vals[ok]
        if eids.size == 0:
            return
        with self._lock:
            self._freq[eids] += vals  # dict keys are unique: no add.at needed

    def frequency(self, eid: int) -> float:
        with self._lock:
            if 0 <= eid < self._freq.shape[0]:
                return float(self._freq[eid])
            return 0.0

    # -- routing -----------------------------------------------------------
    def shard_of_slots(self, archive_slots: np.ndarray) -> np.ndarray:
        """Serving shard per archive slot, via the LIVE routing table
        (vectorized).  The table reference is replaced wholesale by
        rebalance, so reading it without the lock is snapshot-consistent."""
        return self._route[archive_slots]

    def route_of(self, eid: int) -> int:
        """Serving shard this entity routes to; -1 for an unsharded
        coordinate or an entity outside the training index."""
        route = self._route
        if route is None or not 0 <= eid < self._slot_arr.shape[0]:
            return -1
        slot = self._slot_arr[eid]
        return -1 if slot < 0 else int(route[slot])

    def _decay_and_prune(self) -> None:
        """EWMA decay + tracked-set bound; caller holds ``self._lock``.

        Counters at/below the floor zero out (the one-hit long tail);
        ``tracked_max`` prunes the survivors to the top-k by one
        ``argpartition`` pass, so the between-pass state and the next
        ranking are both bounded regardless of how many entities traffic
        touched."""
        f = self._freq
        f *= self.decay
        f[f <= _FREQ_FLOOR] = 0.0
        if self.tracked_max is not None:
            nnz = int(np.count_nonzero(f))
            if nnz > self.tracked_max:
                drop = np.argpartition(-f, self.tracked_max)[self.tracked_max:]
                f[drop] = 0.0

    # -- promotion / demotion ----------------------------------------------
    def rebalance(self) -> Tuple[int, int]:
        """One placement pass: EWMA decay, then frequency-ranked
        promotion/demotion — and, when sharded, the traffic-aware routing
        re-fit plus zipf-head replication (module docstring).

        Unsharded this is the classic pass: rank all entities with
        recorded traffic plus the incumbents by frequency (incumbents win
        ties — hysteresis against churn; archive slot breaks the rest, so
        a fixed request trace yields a reproducible hot set), then scatter
        the promoted rows into the device rows the demoted ones vacate —
        ONE ``.at[rows].set`` launch, table shape unchanged.  Sharded, the
        same ranking runs per shard over the candidates the routing table
        assigns there (plus the replica heads), with promotions paired
        against that shard's explicit free-row pool: never-occupied rows
        first, then the coldest incumbents' rows.  The ranking is a
        ``lexsort`` over the candidate arrays (traffic ∪ incumbents —
        bounded by ``tracked_max`` + capacity), not a Python sort over
        every tracked entity.  Returns (promotions, demotions); sharded,
        demotions can be fewer when free rows absorb the difference.
        """
        spec = self.shard_spec
        if self.hot_capacity < 1 or (
                self.hot_capacity >= self.num_entities
                and (spec is None or self.replicate_top_k == 0)):
            with self._lock:  # keep counters EWMA even when residency is fixed
                self._decay_and_prune()
            return 0, 0
        with obs_span("store.rebalance", coordinate=self.cid), self._lock:
            self._decay_and_prune()
            freq = self._freq
            current = self._hot.slot_of
            if spec is not None:
                promote, rows, demote, slot_of, replicas, route = \
                    self._place_sharded_locked(freq)
                self._route = route
                if promote:
                    payload = self._archive_rows(
                        self._slot_arr[np.asarray(promote, np.int64)])
                    self._hot = self._scatter_rows(self._hot, rows, payload,
                                                   slot_of, replicas)
                elif slot_of != current or replicas != self._hot.replicas:
                    # routing/primary-row change only: same device arrays,
                    # new maps — still one atomic snapshot swap
                    self._hot = self._hot._replace(slot_of=slot_of,
                                                   replicas=replicas)
                else:
                    return 0, 0
            else:
                cur = np.fromiter(current.keys(), np.int64, len(current))
                cand = np.union1d(np.nonzero(freq)[0].astype(np.int64), cur)
                promote, demote = self._rank_moves(cand, cur,
                                                   self.hot_capacity, freq)
                if not promote:
                    return 0, 0
                rows = [current[e] for e in demote]
                new_rows = self._archive_rows(self._slot_arr[promote])
                slot_of = dict(current)
                for e in demote:
                    del slot_of[e]
                for e, r in zip(promote, rows):
                    slot_of[e] = r
                self._hot = self._scatter_rows(self._hot, rows, new_rows,
                                               slot_of)
        self._update_shard_gauges()
        for e in promote:  # device copy supersedes any LRU copy
            self.cold.invalidate(e)
        return len(promote), len(demote)

    def _place_sharded_locked(self, freq: np.ndarray):
        """Traffic-aware sharded placement; caller holds ``self._lock``.

        Returns ``(promote, promote_rows, demote, slot_of, replicas,
        route)``: entities to scatter (paired row-for-row with
        ``promote_rows``), entities losing a row, and the new snapshot
        maps + routing table.  Deterministic end to end — every iteration
        order is sorted or rank-ordered — so two stores fed the same trace
        converge to the same placement."""
        spec = self.shard_spec
        n, cap = spec.n_shards, spec.cap
        current = self._hot.slot_of
        # eid -> every row currently holding its coefficients (replicas
        # included): residency and eviction bookkeeping is per ROW
        rows_of = {e: self._hot.replicas.get(e, (r,))
                   for e, r in current.items()}
        cur = np.sort(np.fromiter(rows_of.keys(), np.int64, len(rows_of)))
        cand = np.union1d(np.nonzero(freq)[0].astype(np.int64), cur)
        route = self._route
        if cand.size == 0:
            return [], [], [], dict(current), dict(self._hot.replicas), route
        ranked = cand[np.lexsort((self._slot_arr[cand], -freq[cand]))]
        # 1) routing re-fit: greedy bin-pack on predicted load, hottest
        # entity placed first; an incumbent keeps its shard inside the
        # slack (hysteresis), overflow spills to the lightest shard
        if self.load_aware:
            route = route.copy()
            load = np.zeros(n, np.float64)
            for e in ranked:
                slot = self._slot_arr[e]
                home = int(route[slot])
                fe = float(freq[e])
                if load[home] > _ROUTE_SLACK * (load.min() + fe):
                    home = int(np.argmin(load))  # ties: lowest shard id
                    route[slot] = home
                load[home] += fe
        # 2) replication candidacy: the zipf head competes for residency
        # on EVERY shard, not just its routed one
        heads = [int(e) for e in ranked[:self.replicate_top_k]
                 if freq[e] > 0.0] if self.replicate_top_k > 0 else []
        # 3) per-shard frequency ranking against explicit row pools
        eid_shard = {int(e): int(route[self._slot_arr[e]]) for e in cand}
        by_shard: List[List[int]] = [[] for _ in range(n)]
        for e in ranked:
            by_shard[eid_shard[int(e)]].append(int(e))
        for s in range(n):
            for e in heads:
                if eid_shard[e] != s:
                    by_shard[s].append(e)
        owner = {r: e for e, rs in rows_of.items() for r in rs}
        new_rows_of: Dict[int, List[int]] = {}
        promote: List[int] = []
        promote_rows: List[int] = []
        demote: List[int] = []
        for s in range(n):
            cand_s = np.asarray(by_shard[s], np.int64)
            base = s * cap
            resident = [(r, owner[r]) for r in range(base, base + cap)
                        if r in owner]
            res_eids = {e for _, e in resident}
            if cand_s.size:
                inc = np.fromiter((int(e) in res_eids for e in cand_s),
                                  bool, cand_s.size)
                sel = np.lexsort((self._slot_arr[cand_s],
                                  np.where(inc, 0, 1), -freq[cand_s]))
                desired = [int(e) for e in cand_s[sel][:cap]]
            else:
                desired = []
            desired_set = set(desired)
            kept, evictable = [], []
            for r, e in resident:
                (kept if e in desired_set else evictable).append((e, r))
            # coldest evicted first; deterministic slot tiebreak — but a
            # not-desired incumbent KEEPS its row until a promotion
            # actually needs it (the same retain-until-reused hysteresis
            # max_moves always implied)
            evictable.sort(key=lambda er: (freq[er[0]],
                                           -int(self._slot_arr[er[0]])))
            free = [r for r in range(base, base + cap) if r not in owner]
            want = [e for e in desired if e not in res_eids]
            nmov = len(want) if self.max_moves is None \
                else min(len(want), self.max_moves)
            pool = free + [r for _, r in evictable]
            nmov = min(nmov, len(pool))
            n_evict = max(0, nmov - len(free))
            promote += want[:nmov]
            promote_rows += pool[:nmov]
            demote += [e for e, _ in evictable[:n_evict]]
            for e, r in (kept + evictable[n_evict:]
                         + list(zip(want[:nmov], pool[:nmov]))):
                new_rows_of.setdefault(e, []).append(r)
        slot_of: Dict[int, int] = {}
        replicas: Dict[int, Tuple[int, ...]] = {}
        for e in sorted(new_rows_of):
            rs = sorted(new_rows_of[e])
            home = eid_shard[e]
            slot_of[e] = next((r for r in rs if r // cap == home), rs[0])
            if len(rs) > 1:
                replicas[e] = tuple(rs)
        return promote, promote_rows, demote, slot_of, replicas, route

    def _rank_moves(self, cand: np.ndarray, cur: np.ndarray, capacity: int,
                    freq: np.ndarray) -> Tuple[List[int], List[int]]:
        """Rank one residency domain (the whole table, or one shard's rows)
        and return (promote, demote) entity lists — always equal length."""
        f = freq[cand]
        incumbent = np.isin(cand, cur, assume_unique=True)
        slots = self._slot_arr[cand]
        # lexsort: last key is primary — (-freq, incumbent-first, slot),
        # the SAME composite key the dict-era sorted() used, so hot sets
        # stay reproducible for a fixed trace
        ranked = cand[np.lexsort((slots, np.where(incumbent, 0, 1), -f))]
        desired = ranked[:capacity]
        promote = desired[~np.isin(desired, cur, assume_unique=True)]
        if promote.size == 0:
            return [], []
        # coldest incumbents vacate first; deterministic tiebreak again
        # (freq ascending, then archive slot DEscending)
        dem = cur[~np.isin(cur, desired, assume_unique=True)]
        demote = dem[np.lexsort((-self._slot_arr[dem], freq[dem]))]
        if self.max_moves is not None:
            promote = promote[: self.max_moves]
            demote = demote[: promote.size]
        return [int(e) for e in promote], [int(e) for e in demote]

    def _update_shard_gauges(self) -> None:
        """Per-shard occupancy gauges (sharded coordinates only)."""
        spec = self.shard_spec
        if spec is None or self._metrics is None or spec.cap < 1:
            return
        occ = np.zeros(spec.n_shards, np.int64)
        hot = self._hot
        for e, row in hot.slot_of.items():
            for r in hot.replicas.get(e, (row,)):  # replicas occupy rows too
                occ[r // spec.cap] += 1
        for sid in range(spec.n_shards):
            self._metrics.set_shard_occupancy(self.cid, sid,
                                              occ[sid] / spec.cap)

    def dense_row(self, eid: int) -> Optional[np.ndarray]:
        """One entity's CURRENT coefficient row as a dense ``[dim]`` copy —
        the warm-start read for online refits (online/trainer.py) and the
        other-coordinate margin term in their offsets.  None for an entity
        this coordinate never trained.  Taken under the lock so a
        concurrent ``apply_delta`` can never hand back a half-written row."""
        with self._lock:
            slot = self.archive_slot_of.get(eid)
            if slot is None:
                return None
            return self._dense_row_locked(slot)

    def _dense_row_locked(self, slot: int) -> np.ndarray:
        return np.array(self._archive[slot])

    # -- streaming deltas --------------------------------------------------
    def apply_delta(self, eid: int, row: np.ndarray) -> bool:
        """Replace one entity's coefficient row in place (online learning).

        ``row`` is always a DENSE [dim] vector on the wire (the trainer's
        natural output); the representation hook converts it — the compact
        coordinate compacts it to (indices, values) under its per-row
        capacity.  Updates the host archive, scatters into the device table
        when the entity is resident, and invalidates its LRU entry — the
        next resolve serves the new row whichever tier it lands on.
        Returns False for an entity this coordinate never trained (serving
        never grows the training-time index)."""
        payload = self._delta_payload(row)
        with self._lock:
            slot = self.archive_slot_of.get(eid)
            if slot is None:
                return False
            self._write_archive_row(slot, payload)
            dev = self._hot.slot_of.get(eid)
            if dev is not None:
                # replica coherence: ONE scatter hits every row holding
                # this entity, all under the same (generation,
                # delta_version) identity — no replica can serve stale
                rows = list(self._hot.replicas.get(eid, (dev,)))
                self._hot = self._scatter_rows(
                    self._hot, rows, self._stack_rows([payload] * len(rows)),
                    self._hot.slot_of)
        self.cold.invalidate(eid)
        return True


class CompactRandomCoordinate(RandomCoordinate):
    """Sparse/compact random-effect coordinate: wide-vocabulary entities
    served NATIVELY from device-resident (indices, values) hot rows — no
    ``.to_dense()`` [E, d_vocab] stack ever exists on host or device.

    The archive is the CompactRandomEffectModel's columnar pair ([E, k]
    int32 column ids padded with ``dim`` + aligned values, exactly the
    container the trainer publishes); the hot set is the same pair's first
    ``hot_capacity`` rows, swapped/rebalanced/delta-patched by the
    inherited frequency machinery with both device arrays replaced as ONE
    ``CompactHotSet`` snapshot.  The engine scores hot rows with the SAME
    compact gather kernel batch scoring uses (models/game
    .score_compact_dense) and cold/overflow rows with the identical math on
    per-sample rows, so compact serving is bitwise the compact batch score.

    Streaming deltas stay dense-[dim] on the wire; rows compact here and a
    delta with more nonzeros than the model's per-row capacity ``k`` is
    refused loudly (growing k would change every AOT executable's shapes —
    the zero-recompile contract; retrain or hot-swap into a roomier k)."""

    kind = "compact"

    def __init__(self, cid: str, feature_shard: str, random_effect_type: str,
                 archive_indices: np.ndarray, archive_values: np.ndarray,
                 dim: int, archive_slot_of: Dict[int, int],
                 hot_capacity: int, lru_capacity: int,
                 metrics: Optional[ServingMetrics] = None,
                 decay: float = 0.5,
                 max_moves: Optional[int] = None,
                 tracked_max: Optional[int] = None,
                 shard_spec: Optional[ShardSpec] = None,
                 load_aware: bool = True,
                 replicate_top_k: int = 0):
        self._full_dim = int(dim)
        super().__init__(cid, feature_shard, random_effect_type,
                         (archive_indices, archive_values), archive_slot_of,
                         hot_capacity, lru_capacity, metrics=metrics,
                         decay=decay, max_moves=max_moves,
                         tracked_max=tracked_max, shard_spec=shard_spec,
                         load_aware=load_aware,
                         replicate_top_k=replicate_top_k)

    # -- row-representation hooks -----------------------------------------
    def _bind_archive(self, archive) -> None:
        idx, val = archive
        if idx.shape != val.shape:
            raise ValueError(
                f"coordinate {self.cid!r}: indices {idx.shape} != values "
                f"{val.shape}")
        self._archive_idx = np.asarray(idx, np.int32)
        self._archive_val = np.asarray(val)
        self.num_entities, self.k = self._archive_idx.shape
        self.dim = self._full_dim  # full vocabulary width (shard contract)

    def _initial_hot(self, slot_of: Dict[int, int]) -> CompactHotSet:
        # unpopulated rows carry all-``dim`` indices — inert to the compact
        # gather, so padding and the all-cold guaranteed row score 0
        rows = self._device_rows()
        idx = np.full((rows, self.k), self.dim, np.int32)
        val = np.zeros((rows, self.k), self._archive_val.dtype)
        if slot_of:
            dev = np.fromiter(slot_of.values(), np.int64, len(slot_of))
            src = self._slot_arr[
                np.fromiter(slot_of.keys(), np.int64, len(slot_of))]
            idx[dev] = self._archive_idx[src]
            val[dev] = self._archive_val[src]
        return CompactHotSet(self._place(idx), self._place(val), slot_of)

    def _archive_rows(self, slots: np.ndarray):
        return self._archive_idx[slots], self._archive_val[slots]

    def _scatter_rows(self, hot: CompactHotSet, dev_rows: List[int], payload,
                      slot_of: Dict[int, int],
                      replicas=None) -> CompactHotSet:
        idx, val = payload
        rows = jnp.asarray(dev_rows, jnp.int32)
        # two scatters, ONE snapshot swap — readers hold the triple and can
        # never pair new values with old column ids
        return CompactHotSet(
            self._repin(hot.indices.at[rows].set(jnp.asarray(idx))),
            self._repin(hot.values.at[rows].set(jnp.asarray(val))),
            slot_of, hot.replicas if replicas is None else replicas)

    def _delta_payload(self, row: np.ndarray):
        row = np.asarray(row, dtype=self._archive_val.dtype)
        if row.shape != (self.dim,):
            raise ValueError(
                f"coordinate {self.cid!r}: delta row has shape {row.shape}, "
                f"expected ({self.dim},)")
        cols = np.nonzero(row)[0]
        if len(cols) > self.k:
            raise ValueError(
                f"coordinate {self.cid!r}: delta row has {len(cols)} nonzero "
                f"coefficients but this compact store's per-row capacity is "
                f"{self.k} — truncation would silently change scores (hot-"
                "swap a model rebuilt with a larger capacity instead)")
        idx = np.full(self.k, self.dim, np.int32)
        val = np.zeros(self.k, self._archive_val.dtype)
        idx[: len(cols)] = cols.astype(np.int32)
        val[: len(cols)] = row[cols]
        return idx, val

    def _write_archive_row(self, slot: int, payload) -> None:
        idx, val = payload
        self._archive_idx[slot] = idx
        self._archive_val[slot] = val

    def _stack_rows(self, payloads: list):
        return (np.stack([p[0] for p in payloads]),
                np.stack([p[1] for p in payloads]))

    def _fetch_cold(self, eid: int):
        slot = self.archive_slot_of.get(eid)
        if slot is None:
            return None
        return self._archive_idx[slot], self._archive_val[slot]

    def _dense_row_locked(self, slot: int) -> np.ndarray:
        row = np.zeros(self.dim, self._archive_val.dtype)
        idx = self._archive_idx[slot]
        ok = idx < self.dim  # dim-padded tail columns are inert
        row[idx[ok]] = self._archive_val[slot][ok]
        return row


class CoefficientStore:
    """One model version, device-ready (see module docstring)."""

    def __init__(self, task: TaskType,
                 coordinates: Dict[str, Union[FixedCoordinate,
                                              RandomCoordinate]],
                 entity_indexes: Dict[str, EntityIndex],
                 index_maps: Dict[str, "IndexMap"],
                 shard_dims: Dict[str, int],
                 config: StoreConfig,
                 version: str = "",
                 metrics: Optional[ServingMetrics] = None,
                 mesh: Optional[Mesh] = None):
        self.task = task
        self.coordinates = coordinates
        self.order: List[str] = list(coordinates)  # additive-score order
        self.entity_indexes = entity_indexes
        self.index_maps = index_maps
        self.shard_dims = shard_dims
        self.config = config
        self.version = version
        self.metrics = metrics
        self.mesh = mesh  # serving mesh when config.mesh_shards > 0
        self.generation = next(_generation)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_bundle(cls, bundle, config: Optional[StoreConfig] = None,
                    version: str = "",
                    metrics: Optional[ServingMetrics] = None,
                    ) -> "CoefficientStore":
        """Build from a storage/model_io.ModelBundle (the load_model_bundle
        result) — the path both cold start (cli/serve.py) and hot swap
        (serving/swap.py) share."""
        return cls.from_model(bundle.model, bundle.task,
                              bundle.entity_indexes, bundle.index_maps,
                              config=config,
                              version=version or bundle.model_dir,
                              metrics=metrics)

    @classmethod
    def from_model(cls, model: GameModel, task: TaskType,
                   entity_indexes: Dict[str, EntityIndex],
                   index_maps: Dict[str, "IndexMap"],
                   config: Optional[StoreConfig] = None,
                   version: str = "",
                   metrics: Optional[ServingMetrics] = None,
                   ) -> "CoefficientStore":
        config = config or StoreConfig()
        coordinates: Dict[str, Union[FixedCoordinate, RandomCoordinate]] = {}
        shard_dims: Dict[str, int] = {}
        mesh = (serving_mesh(config.mesh_shards)
                if config.mesh_shards > 0 else None)

        def _residency(n_ent: int) -> Tuple[int, Optional[ShardSpec]]:
            """(hot_capacity, shard_spec) under the config's policy.

            Sharded, ``device_capacity`` is the per-shard row budget, so the
            table carries ``cap * n_shards`` rows — aggregate hot capacity
            scales with the mesh.  ``cap`` is clamped to ceil(n_ent /
            n_shards): with round-robin routing that is the largest
            population any shard can hold, so a bigger cap would only pin
            dead rows."""
            if mesh is None:
                hot = n_ent if config.device_capacity is None else min(
                    config.device_capacity, n_ent)
                return hot, None
            n = config.mesh_shards
            per = -(-n_ent // n)
            cap = per if config.device_capacity is None else min(
                config.device_capacity, per)
            return cap * n, ShardSpec(mesh=mesh, n_shards=n, cap=cap)

        def _shard_dim(shard: str, d: int, cid: str) -> None:
            have = shard_dims.setdefault(shard, d)
            if have != d:
                raise ValueError(
                    f"coordinate {cid!r}: shard {shard!r} width {d} "
                    f"conflicts with another coordinate's {have}")

        for cid, m in model.models.items():
            if isinstance(m, FixedEffectModel):
                w = np.asarray(m.coefficients.means)
                _shard_dim(m.feature_shard, w.shape[-1], cid)
                coordinates[cid] = FixedCoordinate(
                    cid=cid, feature_shard=m.feature_shard,
                    weights=jnp.asarray(w))
            elif isinstance(m, RandomEffectModel):
                w_stack = np.asarray(m.w_stack)
                n_ent, d = w_stack.shape
                _shard_dim(m.feature_shard, d, cid)
                hot, spec = _residency(n_ent)
                coordinates[cid] = RandomCoordinate(
                    cid=cid, feature_shard=m.feature_shard,
                    random_effect_type=m.random_effect_type,
                    archive=np.array(w_stack),  # own it: deltas mutate rows
                    archive_slot_of=dict(m.slot_of),
                    hot_capacity=hot,
                    lru_capacity=config.lru_capacity,
                    metrics=metrics,
                    decay=config.hot_decay,
                    max_moves=config.hot_max_moves,
                    tracked_max=config.hot_tracked_max,
                    shard_spec=spec,
                    load_aware=config.load_aware_routing,
                    replicate_top_k=config.replicate_top_k)
            elif isinstance(m, CompactRandomEffectModel):
                # wide-vocabulary sparse rows serve NATIVELY: the columnar
                # (indices, values) pair goes device-resident as-is — no
                # [E, d_vocab] .to_dense() stack, on host or device
                idx = np.asarray(m.indices)
                n_ent = idx.shape[0]
                _shard_dim(m.feature_shard, m.dim, cid)
                hot, spec = _residency(n_ent)
                coordinates[cid] = CompactRandomCoordinate(
                    cid=cid, feature_shard=m.feature_shard,
                    random_effect_type=m.random_effect_type,
                    archive_indices=np.array(idx),   # own: deltas mutate
                    archive_values=np.array(np.asarray(m.values)),
                    dim=m.dim,
                    archive_slot_of=dict(m.slot_of),
                    hot_capacity=hot,
                    lru_capacity=config.lru_capacity,
                    metrics=metrics,
                    decay=config.hot_decay,
                    max_moves=config.hot_max_moves,
                    tracked_max=config.hot_tracked_max,
                    shard_spec=spec,
                    load_aware=config.load_aware_routing,
                    replicate_top_k=config.replicate_top_k)
            else:
                raise ValueError(
                    f"coordinate {cid!r}: serving supports FixedEffectModel, "
                    f"dense RandomEffectModel and CompactRandomEffectModel "
                    f"(got {type(m).__name__})")
        for shard, d in shard_dims.items():
            imap = index_maps.get(shard)
            if imap is None:
                raise ValueError(
                    f"feature shard {shard!r} has no index map — requests "
                    "cannot be densified without it")
            if imap.size != d:
                raise ValueError(
                    f"feature shard {shard!r}: index map has {imap.size} "
                    f"features but the model expects {d} — wrong index map "
                    "for this model version")
        return cls(task=task, coordinates=coordinates,
                   entity_indexes=entity_indexes, index_maps=index_maps,
                   shard_dims=shard_dims, config=config, version=version,
                   metrics=metrics, mesh=mesh)

    # -- shape signature (compiled-executable cache key) -------------------
    def signature(self) -> Tuple:
        """Everything that determines compiled-kernel shapes/dtypes.  Two
        model versions with an equal signature share AOT executables, which
        is what makes same-shape hot swaps recompile-free.  Rebalance and
        streaming deltas never change a shape, so a generation's signature
        is stable for its whole life.  ``fleet_axis`` is the model axis:
        same-shape models on the same axis share executables across a
        multi-model ``KernelCache``; a non-default axis forces a private
        compiled family without perturbing any shape."""
        parts = []
        for cid in self.order:
            c = self.coordinates[cid]
            if isinstance(c, FixedCoordinate):
                parts.append(("fixed", cid, c.feature_shard,
                              c.weights.shape, str(c.weights.dtype)))
            elif isinstance(c, CompactRandomCoordinate):
                hs = c.hot
                parts.append(("compact", cid, c.feature_shard, c.dim,
                              hs.indices.shape, str(hs.values.dtype)))
            else:
                parts.append(("random", cid, c.feature_shard,
                              c.table.shape, str(c.table.dtype)))
        return (tuple(parts), tuple(sorted(self.shard_dims.items())),
                str(np.dtype(self.config.x_dtype)),
                int(self.config.mesh_shards),
                str(self.config.fleet_axis))

    # -- lookups -----------------------------------------------------------
    def entity_id(self, re_type: str, name: Optional[str]) -> int:
        """Entity string -> trained int id; -1 when unknown.  READ-ONLY:
        serving must never grow the training-time index."""
        if name is None:
            return -1
        eidx = self.entity_indexes.get(re_type)
        return -1 if eidx is None else eidx.get(str(name))

    def shard_of_request(self, ids: Dict[str, str]) -> int:
        """Serving shard a request's hot-path work lands on, or -1 when it
        has none (unsharded store, unknown entity, no sharded coordinate).
        Routes via the FIRST sharded random coordinate's live table — the
        frontend's per-shard pressure signal; deliberately cheap (one dict
        walk + one table read), since it runs once per admitted request."""
        for cid in self.order:
            c = self.coordinates[cid]
            if isinstance(c, RandomCoordinate) and c.shard_spec is not None:
                eid = self.entity_id(c.random_effect_type,
                                     ids.get(c.random_effect_type))
                return c.route_of(eid) if eid >= 0 else -1
        return -1

    def resolve(self, cid: str, entity_names: Sequence[Optional[str]],
                n_rows: Optional[int] = None,
                metrics: Optional[ServingMetrics] = None
                ) -> Tuple[Array, np.ndarray, np.ndarray]:
        """Per-sample (table, device slots, cold overflow rows) for one
        coordinate, padded to ``n_rows`` (default: no padding).

        ``table`` is the residency snapshot the slots index — callers MUST
        score against the returned table, not a later read of
        ``coordinate.table``, or a concurrent rebalance could tear them
        apart.  ``slots[i]``: device-table row of sample i's entity, or -1
        (cold or unknown — the device kernel scores those 0, the
        reference's missing-entity convention).  Rows past
        ``len(entity_names)`` are padding: slot -1, zero overflow, and NOT
        counted as entity misses.  ``overflow[i]``: the cold entity's host
        coefficient row (zeros for hot/unknown samples); the engine adds
        ``einsum('nd,nd->n', x, overflow)`` so a cold entity scores exactly
        as if its row were in the device table.  Every real lookup feeds
        the coordinate's EWMA hit counters (the rebalance signal).

        COMPACT coordinates return ``(CompactHotSet, slots, (ov_idx,
        ov_val))`` instead: the snapshot is the (indices, values) pair and
        the overflow is per-sample compact rows ([n, k] ``dim``-padded ids
        + values, inert for hot/unknown samples) that the engine scores
        with the same compact gather the device rows use."""
        c = self.coordinates[cid]
        n_real = len(entity_names)
        n_rows = n_real if n_rows is None else n_rows
        compact = isinstance(c, CompactRandomCoordinate)
        with obs_span("store.resolve", coordinate=cid, rows=n_real,
                      kind=c.kind if isinstance(c, RandomCoordinate)
                      else "fixed"):
            hs = c.hot
            slots = np.full(n_rows, -1, np.int32)
            if compact:
                ov_idx = np.full((n_rows, c.k), c.dim, np.int32)
                ov_val = np.zeros((n_rows, c.k), hs.values.dtype)
            else:
                overflow = np.zeros((n_rows, c.dim), hs.table.dtype)
            misses = hot_hits = 0
            hits: Dict[int, int] = {}
            for i, name in enumerate(entity_names):
                eid = self.entity_id(c.random_effect_type, name)
                if eid < 0:
                    misses += 1
                    continue
                hits[eid] = hits.get(eid, 0) + 1
                slot = hs.slot_of.get(eid)
                if slot is not None:
                    reps = hs.replicas.get(eid)
                    if reps:  # spread a replicated head's reads round-robin
                        slot = reps[i % len(reps)]
                    slots[i] = slot
                    hot_hits += 1
                    continue
                row = c.cold.get(eid)
                if row is None:
                    misses += 1
                elif compact:
                    ov_idx[i], ov_val[i] = row
                else:
                    overflow[i] = row
            c.record_hits(hits)
            if metrics is not None:
                if misses:
                    metrics.inc("entity_misses", misses)
                if hot_hits:
                    metrics.inc("hot_hits", hot_hits)
                if c.shard_spec is not None and hits:
                    self._record_shard_stats(cid, c, hits, slots, metrics)
            if compact:
                return hs, slots, (ov_idx, ov_val)
            return hs.table, slots, overflow

    @staticmethod
    def _record_shard_stats(cid: str, c: RandomCoordinate,
                            hits: Dict[int, int], slots: np.ndarray,
                            metrics: ServingMetrics) -> None:
        """Per-shard lookup/hot-hit counters for one resolved batch.

        Lookups route by the LIVE routing table (where the entity WOULD be
        resident); hot hits decompose the resolved global device rows
        (shard-major layout: shard = row // cap).  Together they give the
        per-shard hit rate the obs gauges expose — the load-imbalance
        signal the traffic-aware rebalance consumes."""
        spec = c.shard_spec
        eids = np.fromiter(hits.keys(), np.int64, len(hits))
        cnts = np.fromiter(hits.values(), np.int64, len(hits))
        arch = c._slot_arr[eids]  # record_hits contract: eids are in range
        ok = arch >= 0
        lookups = np.bincount(c.shard_of_slots(arch[ok]),
                              weights=cnts[ok].astype(np.float64),
                              minlength=spec.n_shards)
        hot_rows = slots[slots >= 0]
        hot = np.bincount(hot_rows // max(spec.cap, 1),
                          minlength=spec.n_shards)
        for sid in range(spec.n_shards):
            metrics.observe_shard_batch(cid, sid, int(lookups[sid]),
                                        int(hot[sid]))

    # -- residency management ----------------------------------------------
    def rebalance(self) -> Dict[str, Tuple[int, int]]:
        """Run one promotion/demotion pass on every random coordinate;
        returns cid -> (promotions, demotions)."""
        moves: Dict[str, Tuple[int, int]] = {}
        for cid in self.order:
            c = self.coordinates[cid]
            if isinstance(c, RandomCoordinate):
                moves[cid] = c.rebalance()
        if self.metrics is not None:
            self.metrics.inc("rebalances")
            promoted = sum(p for p, _ in moves.values())
            demoted = sum(d for _, d in moves.values())
            if promoted:
                self.metrics.inc("hot_promotions", promoted)
            if demoted:
                self.metrics.inc("hot_demotions", demoted)
        return moves

    def apply_delta(self, cid: str, entity: Optional[str],
                    row: np.ndarray) -> bool:
        """Streaming coefficient update: replace ``entity``'s row on
        coordinate ``cid`` in the LIVE store (see RandomCoordinate
        .apply_delta).  Returns False for an entity outside the training
        index; raises ValueError for an unknown/fixed coordinate or a row
        of the wrong width."""
        c = self.coordinates.get(cid)
        if c is None:
            raise ValueError(
                f"unknown coordinate {cid!r} (have {self.order})")
        if isinstance(c, FixedCoordinate):
            raise ValueError(
                f"coordinate {cid!r} is a fixed effect — streaming deltas "
                "target per-entity random-effect rows; rotate fixed effects "
                "through a hot swap")
        eid = self.entity_id(c.random_effect_type, entity)
        if eid < 0:
            return False
        with obs_span("store.apply_delta", coordinate=cid):
            ok = c.apply_delta(eid, row)
        if ok and self.metrics is not None:
            self.metrics.inc("delta_updates")
        return ok


class HotSetManager:
    """Background promotion/demotion driver.

    Calls ``store_getter().rebalance()`` every ``interval_s`` on a daemon
    thread — ``store_getter`` (usually ``lambda: engine.store``) re-reads
    the ACTIVE generation each tick, so the manager survives hot swaps
    without re-wiring.  ``run_once`` is the synchronous form benches and
    tests use for deterministic cadence."""

    def __init__(self, store_getter: Callable[[], CoefficientStore],
                 interval_s: float = 1.0):
        self._get = store_getter
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def run_once(self) -> Dict[str, Tuple[int, int]]:
        return self._get().rebalance()

    def start(self) -> "HotSetManager":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="photon-serving-hotset")
            self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.run_once()
