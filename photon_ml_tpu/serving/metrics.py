"""Serving metrics registry: latency histograms, QPS, padding waste, swaps.

Photon ML reference counterpart: the Spark batch scorer has no online
metrics surface; the closest analogs are the reference's Timed{} phase logs
(util/Timed.scala) and the PalDB store's hit accounting that LinkedIn's
serving stack layers on top of the published GLMix artifacts.  Here the
registry is first-class: every serving component (coefficient store,
batcher, engine, hot swap) reports into ONE thread-safe object exported as
JSON, and phase timings flow in through ``utils/logging.Timed``'s ``sink``
hook so the serving path and the offline drivers share one timing idiom.

Metric families:
  - counters: requests, batches, scored samples, entity misses (unknown
    entity -> score 0), hot-set hits / cold fetches / LRU hits (residency
    tiers), hot promotions/demotions + rebalances, streaming delta updates,
    compiles, swaps / swap failures, and the async batcher's flush mix
    (flushes_full / flushes_deadline / flushes_forced);
  - per-bucket latency histograms (log-spaced bins, p50/p99/max) keyed by
    padded bucket size, plus padded-row accounting for the padding-waste
    ratio (padded rows / total padded capacity) and per-bucket occupancy
    (real rows / launched capacity at that bucket size);
  - derived gauges in the snapshot: ``hot_set_hit_rate`` (device-resident
    lookups / all known-entity lookups) and ``entity_miss_rate`` (unknown
    entities / all lookups) — the two numbers the frequency-ranked hot set
    exists to move;
  - phase durations (warm, swap) via the Timed sink.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional

# Log-spaced latency bin upper bounds: 1us .. ~67s, factor 2 per bin.  Fixed
# bins (not reservoirs) so concurrent recording is O(1), allocation-free,
# and snapshots are mergeable across processes.
_BOUNDS = tuple(1e-6 * (2.0 ** i) for i in range(27))


class LatencyHistogram:
    """Fixed-bin latency histogram with percentile estimates.

    Percentiles interpolate inside the containing bin (log-linear would be
    marginally better; linear keeps the math obvious and the error is
    bounded by one 2x bin).
    """

    def __init__(self) -> None:
        self.counts = [0] * (len(_BOUNDS) + 1)
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def record(self, seconds: float) -> None:
        lo, hi = 0, len(_BOUNDS)
        while lo < hi:  # first bin whose bound >= seconds
            mid = (lo + hi) // 2
            if _BOUNDS[mid] < seconds:
                lo = mid + 1
            else:
                hi = mid
        self.counts[lo] += 1
        self.count += 1
        self.total += seconds
        self.min = min(self.min, seconds)
        self.max = max(self.max, seconds)

    def percentile(self, p: float) -> float:
        if self.count == 0:
            return 0.0
        target = p * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if seen + c >= target and c > 0:
                hi = _BOUNDS[i] if i < len(_BOUNDS) else self.max
                lo = _BOUNDS[i - 1] if i > 0 else 0.0
                frac = (target - seen) / c
                return min(lo + frac * (hi - lo), self.max)
            seen += c
        return self.max

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean_s": self.total / self.count if self.count else 0.0,
            "p50_s": self.percentile(0.50),
            "p99_s": self.percentile(0.99),
            "min_s": self.min if self.count else 0.0,
            "max_s": self.max,
        }


class ServingMetrics:
    """Thread-safe registry shared by every serving component.

    All mutators take the one lock — serving requests, the background swap
    thread, and metrics exports may interleave freely.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._latency: Dict[str, LatencyHistogram] = {}
        self._phases: Dict[str, float] = {}
        self._padded_capacity = 0  # sum of bucket sizes actually launched
        self._real_rows = 0        # real (unpadded) rows inside them
        # per-bucket occupancy accounting: bucket size -> [real, capacity]
        self._bucket_rows: Dict[int, list] = {}
        self._started = time.time()

    # -- mutators ----------------------------------------------------------
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def observe_latency(self, key: str, seconds: float) -> None:
        with self._lock:
            h = self._latency.get(key)
            if h is None:
                h = self._latency[key] = LatencyHistogram()
            h.record(seconds)

    def observe_batch(self, bucket: int, real_rows: int, seconds: float) -> None:
        """One launched micro-batch: ``bucket`` padded rows, ``real_rows``
        live ones, per-request latency credited to every live row."""
        with self._lock:
            self._counters["batches"] = self._counters.get("batches", 0) + 1
            self._counters["scored_samples"] = (
                self._counters.get("scored_samples", 0) + real_rows)
            self._padded_capacity += bucket
            self._real_rows += real_rows
            occ = self._bucket_rows.get(bucket)
            if occ is None:
                occ = self._bucket_rows[bucket] = [0, 0]
            occ[0] += real_rows
            occ[1] += bucket
            key = f"bucket_{bucket}"
            h = self._latency.get(key)
            if h is None:
                h = self._latency[key] = LatencyHistogram()
            h.record(seconds)

    def phase(self, label: str, seconds: float) -> None:
        """``utils/logging.Timed`` sink: cumulative wall time per phase."""
        with self._lock:
            self._phases[label] = self._phases.get(label, 0.0) + seconds

    # -- views -------------------------------------------------------------
    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    @property
    def padding_waste_ratio(self) -> float:
        """Fraction of launched device rows that were padding."""
        with self._lock:
            if self._padded_capacity == 0:
                return 0.0
            return 1.0 - self._real_rows / self._padded_capacity

    def snapshot(self) -> dict:
        with self._lock:
            uptime = max(time.time() - self._started, 1e-9)
            requests = self._counters.get("requests", 0)
            waste = (1.0 - self._real_rows / self._padded_capacity
                     if self._padded_capacity else 0.0)
            # residency gauges: lookups = every real (non-padding) entity
            # lookup; hot = served straight from the device table
            hot = self._counters.get("hot_hits", 0)
            lookups = (hot + self._counters.get("lru_hits", 0)
                       + self._counters.get("cold_fetches", 0)
                       + self._counters.get("entity_misses", 0))
            return {
                "counters": dict(self._counters),
                "qps": requests / uptime,
                "uptime_s": uptime,
                "padding_waste_ratio": waste,
                "padded_rows_launched": self._padded_capacity,
                "real_rows_launched": self._real_rows,
                "bucket_occupancy": {
                    f"bucket_{b}": (rows[0] / rows[1] if rows[1] else 0.0)
                    for b, rows in sorted(self._bucket_rows.items())},
                "hot_set_hit_rate": hot / lookups if lookups else 0.0,
                "entity_miss_rate": (
                    self._counters.get("entity_misses", 0) / lookups
                    if lookups else 0.0),
                "latency": {k: h.snapshot()
                            for k, h in sorted(self._latency.items())},
                "phases_s": dict(self._phases),
            }

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent)

    def export(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json(indent=2))
