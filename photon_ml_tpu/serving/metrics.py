"""Serving metrics: a thin facade over the unified ``obs.MetricsRegistry``.

Photon ML reference counterpart: the Spark batch scorer has no online
metrics surface; the closest analogs are the reference's Timed{} phase logs
(util/Timed.scala) and the PalDB store's hit accounting that LinkedIn's
serving stack layers on top of the published GLMix artifacts.

Since the photonscope PR, storage lives in ONE ``obs.MetricsRegistry``
(label-aware counters/gauges/histograms with Prometheus + JSON exporters)
shared by every serving component — this class only maps the serving
domain onto registry families and REPRODUCES the PR-4 ``snapshot()`` wire
format byte-for-byte (key set and semantics), so BENCH_SERVING history
stays comparable across PRs.  ``LatencyHistogram`` is re-exported from
``obs.registry`` for the same compatibility reason.

Registry mapping:
  - plain counters (``requests``, ``hot_hits``, ``swaps``, the flush mix,
    ...) keep their names as unlabeled registry counters;
  - per-bucket latency -> histogram family ``serving_latency_s`` labeled
    ``key="bucket_<n>"`` (plus free-form ``observe_latency`` keys);
  - padding/occupancy accounting -> reserved ``serving_*`` counters
    (``serving_padded_rows``/``serving_real_rows`` unlabeled;
    ``serving_bucket_rows_{real,capacity}`` labeled by bucket) excluded
    from the snapshot's ``counters`` view;
  - per-batch bucket-size counters -> ``serving_batches_total{bucket=..}``
    (the ``requests_total{bucket="64"}``-style series scrapers want);
  - ``Timed`` phase sinks -> accumulating gauge
    ``serving_phase_seconds{phase=...}``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

from photon_ml_tpu.obs.registry import (LatencyHistogram,  # noqa: F401
                                        MetricsRegistry)

# registry families owned by the facade's padding/occupancy bookkeeping —
# internal storage, not part of the snapshot's "counters" wire view
_PADDED = "serving_padded_rows"
_REAL = "serving_real_rows"
_BUCKET_REAL = "serving_bucket_rows_real"
_BUCKET_CAP = "serving_bucket_rows_capacity"
_BATCHES_BY_BUCKET = "serving_batches_total"
_LATENCY = "serving_latency_s"
_PHASE = "serving_phase_seconds"
# pod-slice serving: per-(coordinate, shard) residency + traffic families.
# All labeled, so they ride the Prometheus export and stay OUT of the
# snapshot()'s byte-compatible ``counters`` view automatically.
_SHARD_LOOKUPS = "serving_shard_lookups_total"
_SHARD_HOT = "serving_shard_hot_hits_total"
_SHARD_OCCUPANCY = "serving_shard_occupancy"
_SHARD_PRESSURE = "serving_shard_pressure"
# multi-model serving (serving/fleet): per-(model, tenant) traffic, shadow
# score drift, and per-tenant hot-row budget occupancy.  Labeled families
# like the shard ones — Prometheus export only, never the snapshot.
_FLEET_REQUESTS = "fleet_requests_total"
_FLEET_SHADOW_PAIRS = "fleet_shadow_pairs_total"
_FLEET_SHADOW_DRIFT = "fleet_shadow_drift"
_FLEET_TENANT_ROWS = "fleet_tenant_rows"
_RESERVED = {_PADDED, _REAL}


class ServingMetrics:
    """Thread-safe serving metrics registry (facade; see module docstring).

    All mutation delegates to the one registry lock — serving requests, the
    background swap thread, and metrics exports may interleave freely.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self.registry = registry or MetricsRegistry()
        self._started = time.time()

    # -- mutators ----------------------------------------------------------
    def inc(self, name: str, n: int = 1) -> None:
        self.registry.inc(name, n)

    def observe_latency(self, key: str, seconds: float) -> None:
        self.registry.observe(_LATENCY, seconds, key=key)

    def observe_batch(self, bucket: int, real_rows: int, seconds: float) -> None:
        """One launched micro-batch: ``bucket`` padded rows, ``real_rows``
        live ones, per-request latency credited to every live row."""
        r = self.registry
        r.inc("batches")
        r.inc("scored_samples", real_rows)
        r.inc(_PADDED, bucket)
        r.inc(_REAL, real_rows)
        r.inc(_BUCKET_REAL, real_rows, bucket=bucket)
        r.inc(_BUCKET_CAP, bucket, bucket=bucket)
        r.inc(_BATCHES_BY_BUCKET, 1, bucket=bucket)
        r.observe(_LATENCY, seconds, key=f"bucket_{bucket}")

    def phase(self, label: str, seconds: float) -> None:
        """``utils/logging.Timed`` sink: cumulative wall time per phase."""
        self.registry.add_gauge(_PHASE, seconds, phase=label)

    def observe_shard_batch(self, cid: str, shard: int, lookups: int,
                            hot_hits: int) -> None:
        """One resolved batch's traffic attributed to one mesh shard:
        ``lookups`` entity lookups routed to it (by archive-slot routing),
        ``hot_hits`` of them served from its device rows.  The per-shard
        hit rate these two imply is the pod-slice load-imbalance signal."""
        if lookups:
            self.registry.inc(_SHARD_LOOKUPS, lookups,
                              coordinate=cid, shard=str(shard))
        if hot_hits:
            self.registry.inc(_SHARD_HOT, hot_hits,
                              coordinate=cid, shard=str(shard))

    def set_shard_occupancy(self, cid: str, shard: int, frac: float) -> None:
        """Fraction of one shard's hot-row budget currently resident."""
        self.registry.set_gauge(_SHARD_OCCUPANCY, float(frac),
                                coordinate=cid, shard=str(shard))

    def set_shard_pressure(self, shard: int, seconds: float) -> None:
        """The frontend's estimate of the backlog wait attributable to one
        mesh shard — the per-shard signal AdmissionController's
        ``shard_budget_s`` latch decides on.  Labeled family: Prometheus
        export only, never the ``snapshot()`` wire view."""
        self.registry.set_gauge(_SHARD_PRESSURE, float(seconds),
                                shard=str(shard))

    def shard_view(self) -> dict:
        """Per-(coordinate, shard) residency/traffic summary — a SEPARATE
        view; ``snapshot()``'s key set is a compatibility contract and does
        not grow.  Returns ``{cid: {shard: {lookups, hot_hits, hit_rate,
        occupancy}}}``."""
        r = self.registry
        out: dict = {}

        def _cell(lk):
            d = dict(lk)
            return out.setdefault(d["coordinate"], {}).setdefault(
                int(d["shard"]), {"lookups": 0, "hot_hits": 0,
                                  "hit_rate": 0.0, "occupancy": 0.0})

        for lk, v in r.counter_series(_SHARD_LOOKUPS).items():
            _cell(lk)["lookups"] = int(v)
        for lk, v in r.counter_series(_SHARD_HOT).items():
            _cell(lk)["hot_hits"] = int(v)
        for lk, v in r.gauge_series(_SHARD_OCCUPANCY).items():
            _cell(lk)["occupancy"] = float(v)
        for shards in out.values():
            for cell in shards.values():
                if cell["lookups"]:
                    cell["hit_rate"] = cell["hot_hits"] / cell["lookups"]
        return out

    def observe_fleet_request(self, model: str, tenant: str,
                              n: int = 1) -> None:
        """Requests routed to one (model, tenant) pair — the end-to-end
        per-tenant label the fleet edge stamps on every admit."""
        self.registry.inc(_FLEET_REQUESTS, n, model=model, tenant=tenant)

    def observe_shadow_drift(self, model: str, bucket: int,
                             drift: float) -> None:
        """One primary-vs-shadow score pair's absolute drift, bucketed by
        the micro-batch bucket it scored under (serving/fleet/shadow.py)."""
        self.registry.inc(_FLEET_SHADOW_PAIRS, 1, model=model)
        self.registry.observe(_FLEET_SHADOW_DRIFT, float(drift),
                              model=model, bucket=str(bucket))

    def set_tenant_rows(self, tenant: str, used: int, quota: int) -> None:
        """One tenant's device hot-row budget: rows allocated vs quota."""
        self.registry.set_gauge(_FLEET_TENANT_ROWS, int(used),
                                tenant=tenant, kind="used")
        self.registry.set_gauge(_FLEET_TENANT_ROWS, int(quota),
                                tenant=tenant, kind="quota")

    def fleet_view(self) -> dict:
        """Multi-model summary — a SEPARATE view like ``shard_view``;
        ``snapshot()``'s key set is a compatibility contract and does not
        grow.  Returns ``{"requests": {model: {tenant: n}},
        "shadow": {model: {pairs, drift: {bucket: snapshot}}},
        "tenant_rows": {tenant: {used, quota}}}``."""
        r = self.registry
        requests: dict = {}
        for lk, v in r.counter_series(_FLEET_REQUESTS).items():
            d = dict(lk)
            requests.setdefault(d["model"], {})[d["tenant"]] = int(v)
        shadow: dict = {}
        for lk, v in r.counter_series(_FLEET_SHADOW_PAIRS).items():
            d = dict(lk)
            shadow.setdefault(d["model"], {"pairs": 0, "drift": {}})
            shadow[d["model"]]["pairs"] = int(v)
        for lk, snap in r.histogram_series(_FLEET_SHADOW_DRIFT).items():
            d = dict(lk)
            cell = shadow.setdefault(d["model"], {"pairs": 0, "drift": {}})
            cell["drift"][d["bucket"]] = snap
        tenant_rows: dict = {}
        for lk, v in r.gauge_series(_FLEET_TENANT_ROWS).items():
            d = dict(lk)
            tenant_rows.setdefault(d["tenant"], {})[d["kind"]] = int(v)
        return {"requests": requests, "shadow": shadow,
                "tenant_rows": tenant_rows}

    def watch_state(self) -> dict:
        """The photonwatch federation pull unit (the ``/watchz`` route): the
        full structured registry dump wrapped with this process's label and
        an exporter-side timestamp, so a :class:`~photon_ml_tpu.obs.watch.
        FleetView` can merge and age it.  A SEPARATE view like
        ``shard_view`` — ``snapshot()``'s key set does not grow."""
        from photon_ml_tpu.obs.trace import get_process_label
        return {
            "label": get_process_label() or f"pid-{os.getpid()}",
            "at_unix": time.time(),
            "full": True,
            **self.registry.export_state(),
        }

    # -- views -------------------------------------------------------------
    def counter(self, name: str) -> int:
        return int(self.registry.counter(name))

    def _plain_counters(self) -> dict:
        """The PR-4 ``counters`` view: every unlabeled, non-reserved
        counter (exactly what ``inc``/``observe_batch`` wrote)."""
        out = {}
        for (name, labels), v in self.registry.snapshot_raw_counters():
            if not labels and name not in _RESERVED:
                out[name] = v
        return out

    @property
    def padding_waste_ratio(self) -> float:
        """Fraction of launched device rows that were padding."""
        padded = self.registry.counter(_PADDED)
        if padded == 0:
            return 0.0
        return 1.0 - self.registry.counter(_REAL) / padded

    def snapshot(self) -> dict:
        r = self.registry
        uptime = max(time.time() - self._started, 1e-9)
        counters = self._plain_counters()
        requests = counters.get("requests", 0)
        padded = r.counter(_PADDED)
        real = r.counter(_REAL)
        waste = 1.0 - real / padded if padded else 0.0
        # residency gauges: lookups = every real (non-padding) entity
        # lookup; hot = served straight from the device table
        hot = counters.get("hot_hits", 0)
        lookups = (hot + counters.get("lru_hits", 0)
                   + counters.get("cold_fetches", 0)
                   + counters.get("entity_misses", 0))
        occupancy = {}
        caps = r.counter_series(_BUCKET_CAP)
        reals = r.counter_series(_BUCKET_REAL)
        for lk, cap in sorted(caps.items(),
                              key=lambda e: int(dict(e[0])["bucket"])):
            b = dict(lk)["bucket"]
            occupancy[f"bucket_{b}"] = reals.get(lk, 0) / cap if cap else 0.0
        latency = {dict(lk).get("key", ""): snap
                   for lk, snap in r.histogram_series(_LATENCY).items()}
        phases = {dict(lk).get("phase", ""): v
                  for lk, v in r.gauge_series(_PHASE).items()}
        return {
            "counters": counters,
            "qps": requests / uptime,
            "uptime_s": uptime,
            "padding_waste_ratio": waste,
            "padded_rows_launched": padded,
            "real_rows_launched": real,
            "bucket_occupancy": occupancy,
            "hot_set_hit_rate": hot / lookups if lookups else 0.0,
            "entity_miss_rate": (counters.get("entity_misses", 0) / lookups
                                 if lookups else 0.0),
            "latency": {k: latency[k] for k in sorted(latency)},
            "phases_s": phases,
        }

    def to_json(self, indent: Optional[int] = None) -> str:
        return json.dumps(self.snapshot(), indent=indent)

    def to_prometheus(self) -> str:
        """Prometheus text exposition of the backing registry (every
        serving family, labels included)."""
        return self.registry.to_prometheus()

    def to_openmetrics(self) -> str:
        """OpenMetrics 1.0.0 exposition with histogram exemplars — what
        ``/metrics`` serves when the scrape endpoint is built with
        ``exemplars=True`` (photonpulse trace-id bucket exemplars)."""
        return self.registry.to_openmetrics()

    def export(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json(indent=2))
