"""Wire framing for the network front end: bounded newline-delimited JSON.

Photon ML reference counterpart: none — the reference's online edge is
LinkedIn infrastructure.  The TPU-native stack speaks the SAME wire format
as ``cli/serve.py`` (one JSON object per line, ``{"cmd": ...}`` control
lines, blank line = force flush), so every existing driver works unchanged
over a socket.

The one thing a multi-client edge must add to the stdio loop is a HARD
per-line byte bound: an unbounded ``readline`` lets a single malformed (or
malicious) client grow the server's receive buffer without limit — one
firehose of garbage OOMs every other client's server.  Both framing paths
here enforce ``max_line_bytes``:

  - :class:`BoundedLineReader` — the asyncio side.  Buffers reads itself
    (``asyncio.StreamReader.readline``'s over-limit behavior clears its
    internal buffer mid-line, which would resynchronize on GARBAGE — the
    tail of the oversized line would parse as a fresh request).  An
    oversized line is discarded THROUGH its terminating newline and
    surfaced as one :class:`LineTooLong`, after which the stream is
    byte-exactly aligned on the next line — the connection survives.
  - :func:`iter_bounded_lines` — the same contract for the blocking stdio
    loop (``cli/serve.py`` without ``--listen``), yielding ``LineTooLong``
    markers in-band so the driver replies ``{"error": ...}`` and keeps
    reading.  (Text-mode ``readline(size)`` counts characters, not bytes;
    for the ASCII-dominated JSON wire format the bound is byte-accurate,
    and for exotic unicode it is conservative within the UTF-8 expansion
    factor.)
"""

from __future__ import annotations

import json
from typing import Awaitable, Callable, Iterator, Optional, Union

# 1 MiB: a scoring request is a few hundred bytes; the largest legitimate
# line is a {"cmd": "delta"} row for a wide coordinate (8 bytes/coeff as
# JSON text -> ~100k features fit with headroom)
DEFAULT_MAX_LINE_BYTES = 1 << 20

_READ_CHUNK = 1 << 16


class LineTooLong(ValueError):
    """One wire line exceeded the byte bound (the line was discarded and
    the stream is aligned on the next one)."""

    def __init__(self, nbytes: int, limit: int):
        super().__init__(
            f"line too long: {nbytes} bytes exceeds the "
            f"{limit}-byte limit")
        self.nbytes = nbytes
        self.limit = limit


def error_reply(message: str, **extra) -> dict:
    out = {"error": message}
    out.update(extra)
    return out


def encode(obj: dict) -> bytes:
    """One reply line, wire-ready."""
    return (json.dumps(obj) + "\n").encode("utf-8")


class BoundedLineReader:
    """Newline framing over an async ``read(n) -> bytes`` with a hard
    per-line bound (see module docstring).

    ``readline`` returns the next line (terminator stripped), ``None`` at
    EOF, or raises :class:`LineTooLong` exactly once per oversized line —
    the oversized bytes are consumed through their newline first, so the
    caller may keep reading.
    """

    def __init__(self, read: Callable[[int], Awaitable[bytes]],
                 max_line_bytes: int = DEFAULT_MAX_LINE_BYTES):
        if max_line_bytes < 1:
            raise ValueError(
                f"max_line_bytes must be >= 1, got {max_line_bytes}")
        self._read = read
        self._buf = bytearray()
        self._eof = False
        self.max_line_bytes = int(max_line_bytes)

    async def readline(self) -> Optional[bytes]:
        discarded = 0  # bytes of an oversized line already thrown away
        while True:
            nl = self._buf.find(b"\n")
            if nl >= 0:
                line = bytes(self._buf[: nl])
                del self._buf[: nl + 1]
                # nl > bound catches an oversized line whose newline arrived
                # in the same chunk (it never hit the no-newline path below)
                if discarded or nl > self.max_line_bytes:
                    raise LineTooLong(discarded + nl + 1,
                                      self.max_line_bytes)
                return line
            if len(self._buf) > self.max_line_bytes:
                # no newline yet and already over budget: switch to discard
                # mode — drop what we hold, keep consuming until the line
                # ends so the NEXT line starts clean
                discarded += len(self._buf)
                self._buf.clear()
            if self._eof:
                if discarded:
                    discarded += len(self._buf)
                    self._buf.clear()
                    raise LineTooLong(discarded, self.max_line_bytes)
                if not self._buf:
                    return None
                line = bytes(self._buf)  # trailing line without newline
                self._buf.clear()
                return line
            chunk = await self._read(_READ_CHUNK)
            if not chunk:
                self._eof = True
            else:
                self._buf.extend(chunk)

    async def readexactly(self, n: int) -> bytes:
        """Exactly ``n`` raw bytes from the stream (photonrepl's snapshot
        tarstream rides between two framed lines).  The line bound does not
        apply — the caller announced the byte count in a bounded control
        line first.  Raises :class:`EOFError` on a short stream."""
        if n < 0:
            raise ValueError(f"readexactly: negative count {n}")
        while len(self._buf) < n and not self._eof:
            chunk = await self._read(_READ_CHUNK)
            if not chunk:
                self._eof = True
            else:
                self._buf.extend(chunk)
        if len(self._buf) < n:
            raise EOFError(
                f"stream ended after {len(self._buf)} of {n} bytes")
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out


def iter_bounded_lines(f, max_line_bytes: int = DEFAULT_MAX_LINE_BYTES
                       ) -> Iterator[Union[str, LineTooLong]]:
    """Bounded line iteration for a blocking text stream (the stdio serve
    loop).  Yields each line (newline kept, like file iteration) or a
    :class:`LineTooLong` marker for a discarded oversized line."""
    if max_line_bytes < 1:
        raise ValueError(f"max_line_bytes must be >= 1, got {max_line_bytes}")
    while True:
        line = f.readline(max_line_bytes + 1)
        if not line:
            return
        if len(line) <= max_line_bytes or line.endswith("\n"):
            # within budget, or the terminator landed exactly on the probe
            # boundary (content is <= the bound either way)
            yield line
            continue
        n = len(line)
        while True:  # discard through the end of the oversized line
            more = f.readline(max_line_bytes + 1)
            n += len(more)
            if not more or more.endswith("\n"):
                break
        yield LineTooLong(n, max_line_bytes)
