"""Open-loop Poisson load generator for the socket front end.

Photon ML reference counterpart: none.  The methodology point comes from
the Spark-perf study in PAPERS.md: a CLOSED-loop benchmark (send, wait for
the reply, send the next) self-throttles — when the server slows down the
offered load drops with it, so queueing cliffs are invisible and p99 looks
flat right through saturation.  An OPEN-loop generator fixes the arrival
process instead: requests fire at exponentially-spaced (Poisson) instants
drawn up front from a seeded RNG, whether or not earlier replies have come
back.  Past saturation the backlog grows at (arrival - service) rate and
latency diverges — unless the server sheds, which is exactly the behavior
``bench.py --serving --open-loop`` tracks: below saturation shed≈0, past
it p99 stays bounded near the admission budget while the shed rate (not
the latency) absorbs the excess.

Arrivals are split round-robin across ``n_connections`` persistent
connections so the fairness layer sees multiple clients and no single
kernel socket buffer serializes the offered load.  Each connection has an
asyncio sender (fires at the precomputed schedule) and a reader (matches
``uid`` to its timestamps).

Two latencies are recorded per reply, because a sender that falls behind
schedule silently under-reports otherwise (**coordinated omission**): when
the client loop can't fire at the drawn instant — its own event loop is
busy, or ``drain()`` blocked on a full socket buffer — the send-to-reply
clock starts late and the delay the request REALLY experienced (from its
scheduled Poisson arrival) never shows up in the send-based percentiles.
``latency_ms`` is the raw send-instant→reply number (comparable with
earlier BENCH_NET history); ``latency_corrected_ms`` measures from the
scheduled arrival instant on a schedule clock shared by every sender —
the honest open-loop number.  ``max_send_lag_ms`` reports how far the
generator fell behind its own schedule, so a sweep point where the two
percentile sets diverge is diagnosable as client-side lag rather than
server queueing.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import time
from typing import Callable, Dict, List, Optional

import numpy as np


@dataclasses.dataclass
class OpenLoopResult:
    """One arrival-rate point of the sweep."""

    rate_qps: float          # offered (configured) arrival rate
    duration_s: float        # configured generation window
    offered: int             # arrivals actually fired
    completed: int           # {"score": ...} replies
    shed: int                # {"error": "overloaded"} replies
    errors: int              # any other {"error": ...} reply
    lost: int                # fired but no reply (should be 0)
    achieved_qps: float      # offered / wall time of the send phase
    latency_ms: Dict[str, float]  # RAW send->reply p50 / p99 / p999
    # scheduled-arrival->reply percentiles (coordinated-omission corrected)
    latency_corrected_ms: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    max_send_lag_ms: float = 0.0  # worst sender lag behind the schedule

    @property
    def shed_rate(self) -> float:
        return self.shed / self.offered if self.offered else 0.0

    def to_json(self) -> dict:
        out = dataclasses.asdict(self)
        out["shed_rate"] = round(self.shed_rate, 6)
        return out


def _percentiles(latencies_s: List[float]) -> Dict[str, float]:
    if not latencies_s:
        return {"p50": 0.0, "p99": 0.0, "p999": 0.0}
    arr = np.asarray(latencies_s, dtype=np.float64) * 1e3
    return {"p50": round(float(np.percentile(arr, 50)), 4),
            "p99": round(float(np.percentile(arr, 99)), 4),
            "p999": round(float(np.percentile(arr, 99.9)), 4)}


async def measure_closed_loop_capacity(host: str, port: int,
                                       make_request: Callable[[int], dict],
                                       n: int = 2048,
                                       window: int = 128) -> float:
    """Closed-loop capacity probe: keep ``window`` requests outstanding on
    one connection until ``n`` have round-tripped; returns completed qps.

    This measures the capacity of the WHOLE edge — JSON encode/decode,
    socket, event loop, fairness, batcher, engine — which is what an
    open-loop sweep must be calibrated against (the raw engine's
    full-bucket throughput overstates it several-fold).  Running it also
    warms the batcher's flush-cost EWMA with load-realistic observations,
    so the admission controller enters the sweep calibrated rather than
    at its optimistic floor.
    """
    reader, writer = await asyncio.open_connection(host, port)
    sem = asyncio.Semaphore(window)
    done = 0

    async def read_replies() -> None:
        nonlocal done
        while done < n:
            line = await reader.readline()
            if not line:
                return
            if not line.strip():
                continue
            done += 1
            sem.release()

    rx = asyncio.ensure_future(read_replies())
    t0 = time.perf_counter()
    for uid in range(n):
        await sem.acquire()
        writer.write((json.dumps(make_request(uid)) + "\n").encode("utf-8"))
        if uid % 16 == 0:
            await writer.drain()
    writer.write(b"\n")
    await writer.drain()
    await asyncio.wait_for(rx, timeout=60.0)
    dt = time.perf_counter() - t0
    writer.close()
    return n / dt if dt > 0 else 0.0


async def run_open_loop(host: str, port: int, rate_qps: float,
                        duration_s: float,
                        make_request: Callable[[int], dict],
                        n_connections: int = 4,
                        rng: Optional[np.random.Generator] = None,
                        settle_s: float = 10.0) -> OpenLoopResult:
    """Drive one open-loop point against a listening front end.

    ``make_request(uid) -> dict`` builds each wire request; uids are
    assigned 0..n-1 in arrival order and must round-trip in replies.
    After the send window a blank line flushes each connection and the
    readers get ``settle_s`` to collect stragglers.
    """
    if rate_qps <= 0 or duration_s <= 0:
        raise ValueError("rate_qps and duration_s must be > 0")
    rng = rng or np.random.default_rng(0)
    n = max(1, int(round(rate_qps * duration_s)))
    # Poisson process: exponential inter-arrival gaps, drawn up front so
    # the schedule is independent of server behavior (the open loop)
    arrivals = np.cumsum(rng.exponential(1.0 / rate_qps, size=n))

    conns = []
    for _ in range(n_connections):
        reader, writer = await asyncio.open_connection(host, port)
        conns.append((reader, writer))

    sent_at: Dict[int, float] = {}
    latencies: List[float] = []
    latencies_corrected: List[float] = []
    max_lag = 0.0
    counts = {"completed": 0, "shed": 0, "errors": 0}
    pending = set(range(n))
    all_done = asyncio.Event()
    # ONE schedule clock for every sender: scheduled instant of uid is
    # t_start + arrivals[uid], and corrected latency is measured from it —
    # a per-sender clock would hide exactly the lag being corrected for
    t_start = time.perf_counter()

    async def read_replies(reader: asyncio.StreamReader) -> None:
        while pending:
            line = await reader.readline()
            if not line:
                return
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                counts["errors"] += 1
                continue
            uid = obj.get("uid")
            now = time.perf_counter()
            if uid in pending:
                pending.discard(uid)
                if "score" in obj:
                    counts["completed"] += 1
                    latencies.append(now - sent_at[uid])
                    latencies_corrected.append(
                        now - (t_start + arrivals[uid]))
                elif obj.get("error") == "overloaded":
                    counts["shed"] += 1
                else:
                    counts["errors"] += 1
            elif "error" in obj:
                counts["errors"] += 1
            if not pending:
                all_done.set()

    async def send_arrivals(conn_idx: int) -> None:
        nonlocal max_lag
        _, writer = conns[conn_idx]
        for uid in range(conn_idx, n, n_connections):
            # fire at the SCHEDULED instant, not request-after-response;
            # yield even when behind schedule so this sender's hot loop
            # cannot starve the reply readers sharing the client loop
            # (that would bill server latency for client-side buffering)
            delay = arrivals[uid] - (time.perf_counter() - t_start)
            await asyncio.sleep(delay if delay > 0 else 0)
            now = time.perf_counter()
            sent_at[uid] = now
            lag = now - (t_start + arrivals[uid])
            if lag > max_lag:
                max_lag = lag
            writer.write((json.dumps(make_request(uid)) + "\n")
                         .encode("utf-8"))
            await writer.drain()
        writer.write(b"\n")  # blank line: flush whatever is batching
        await writer.drain()

    readers = [asyncio.ensure_future(read_replies(r)) for r, _ in conns]
    await asyncio.gather(*(send_arrivals(i)
                           for i in range(n_connections)))
    send_wall = time.perf_counter() - t_start
    try:
        await asyncio.wait_for(all_done.wait(), timeout=settle_s)
    except asyncio.TimeoutError:
        pass  # stragglers counted as lost below
    for task in readers:
        task.cancel()
    for _, writer in conns:
        try:
            writer.close()
        except Exception:
            pass

    return OpenLoopResult(
        rate_qps=rate_qps, duration_s=duration_s, offered=n,
        completed=counts["completed"], shed=counts["shed"],
        errors=counts["errors"], lost=len(pending),
        achieved_qps=round(n / send_wall, 2) if send_wall > 0 else 0.0,
        latency_ms=_percentiles(latencies),
        latency_corrected_ms=_percentiles(latencies_corrected),
        max_send_lag_ms=round(max_lag * 1e3, 4))
