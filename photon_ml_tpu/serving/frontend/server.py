"""photonfront: asyncio socket front end over the AsyncBatcher.

Photon ML reference counterpart: none — the reference publishes models and
LinkedIn's serving infrastructure owns the edge.  This module IS that edge
for the TPU-native stack: a stdlib-only asyncio TCP server that multiplexes
many concurrent client connections into the existing
``serving.batcher.AsyncBatcher`` / AOT ``ScoringEngine``, speaking the SAME
newline-delimited JSON wire protocol as the stdio ``cli/serve.py`` loop
(requests, blank-line flush, ``{"cmd": ...}`` control lines), so existing
drivers work unchanged pointed at a socket.

What makes it an edge rather than a socket wrapper:

  - **Admission control / load shedding** (``admission.py``): every score
    request is checked against a deadline budget BEFORE it joins the
    queue, using the batcher's flush-latency EWMA times the queued flush
    waves (``AsyncBatcher.queue_wait_estimate``).  Refusals are explicit —
    ``{"error": "overloaded", "retry_after_ms": ...}`` — and hysteresis
    (two watermarks) keeps the shed decision latched until the backlog
    genuinely drains, so shedding is stable, not flappy.
  - **Per-client fairness** (``fairness.py``): admitted requests queue per
    connection and a round-robin dispatcher fills a bounded batcher window
    (default 2 flush waves), so one firehose connection cannot park a
    trickle client behind its backlog; any client's added wait is bounded
    by (clients x window), not by another client's queue depth.
  - **Graceful drain**: ``{"cmd": "swap"}``, ``{"cmd": "delta"}``,
    ``{"cmd": "shutdown"}`` and SIGTERM (wired in cli/serve.py) stop
    admitting (shed reason ``draining``), submit everything queued, flush
    the batcher, and wait for every in-flight future to resolve before
    flipping the generation / applying the delta / exiting — zero admitted
    requests are ever dropped or errored by a rotation.
  - **Bounded reads** (``protocol.py``): a malformed line gets an
    ``{"error": ...}`` reply and the connection survives; an oversized
    line is discarded through its newline under a hard byte bound, so one
    client cannot OOM the server.

  - **Per-client admission budgets** (``AdmissionConfig.client_budget_s``,
    off by default): before the global deadline check, the wait a client's
    OWN backlog explains is tested against a per-connection budget with
    its own hysteresis latch — a single firehose connection sheds with
    reason ``client_overload`` while everyone else keeps being admitted.
  - **Connection cap** (``FrontendConfig.max_connections``): accepts past
    the cap get one ``{"error": "too_many_connections"}`` line and a clean
    close before any per-connection state is allocated.
  - **Shared-secret auth** (``FrontendConfig.auth_token``, off by
    default): the FIRST line of every connection must then be
    ``{"cmd": "auth", "token": "..."}``; the compare is constant-time
    (``hmac.compare_digest``) and anything else — wrong token, missing
    line, timeout — gets exactly one ``{"error": "unauthorized"}`` frame
    and a close (``front_auth_failures_total``).  A good token is answered
    with ``{"auth": "ok"}`` and the normal wire protocol follows.

Observability: photonscope spans/instants ``front.accept`` /
``front.admit`` / ``front.shed`` / ``front.refuse`` / ``front.drain`` and
registry series ``front_connections`` (gauge),
``front_connections_total``, ``front_connections_refused_total``,
``front_requests_total``, ``front_queue_depth{client=...}``,
``requests_shed_total{reason=...}``, ``front_protocol_errors_total{kind=
...}``, ``front_shedding``, ``front_client_shedding{client=...}``,
``front_predicted_wait_s`` (histogram) — all in the engine's registry,
scrapeable via ``metrics_http.py``.

Concurrency model: ALL front-end state (fair queue, admission latch,
in-flight accounting) is owned by the event loop; the only cross-thread
edges are ``AsyncBatcher.submit`` (thread-safe by contract) and future
completion callbacks, which re-enter the loop via
``call_soon_threadsafe``.  Per-connection reply ORDER is the submission
order: each connection has a reply queue of futures its writer task awaits
in sequence, so fairness reorders work ACROSS clients, never within one.

Wire protocol extension over stdio: ``{"cmd": "shutdown"}`` drains and
stops the whole server (the socket analog of stdin EOF).

Fleet mode (``fleet=ModelFleet(...)``): requests grow an optional
``"model"`` field (absent -> the default model, so pre-fleet clients work
unchanged) routed to per-model ``AsyncBatcher``s that score through a
``FleetRouter`` — the seam canary episodes and shadow scorers interpose
on.  Tenancy rides the same edge: tenant tokens
(``FrontendConfig.tenant_tokens``) scope a connection to one tenant's
models, per-tenant admission budgets (``AdmissionConfig.tenant_budget_s``)
latch shed reason ``tenant_overload`` against the tenant's own
admitted-unsettled backlog, and every admit is attributed to its
``(model, tenant)`` pair in the labeled ``fleet_*`` metric families.
Control commands gain ``fleet`` / ``canary`` / ``promote`` / ``rollback``
/ ``shadow`` plus an optional ``"model"`` field on ``swap`` / ``delta`` /
``rebalance``; all policy transitions run behind the same quiesce barrier
as hot swap, so zero admitted requests are lost across a rollback.  A
wired ``HealthState`` adds /readyz-driven shedding (reason ``not_ready``),
and ``trace_sample_n`` turns always-on tracing into deterministic 1-in-N
sampling at the admission edge.
"""

from __future__ import annotations

import asyncio
import dataclasses
import hmac
import json
import logging
import threading
import time
from typing import Dict, Optional, Tuple

from photon_ml_tpu.chaos.injector import fault as _chaos_fault
from photon_ml_tpu.obs.pulse import clock as pulse_clock
from photon_ml_tpu.obs.pulse.context import bind as ctx_bind
from photon_ml_tpu.obs.pulse.context import maybe_mint as ctx_maybe_mint
from photon_ml_tpu.obs.pulse.context import mint as ctx_mint
from photon_ml_tpu.obs.pulse.flight import get_flight
from photon_ml_tpu.obs.trace import enabled as obs_enabled
from photon_ml_tpu.obs.trace import get_process_label, get_tracer
from photon_ml_tpu.obs.trace import instant as obs_instant
from photon_ml_tpu.obs.trace import span as obs_span
from photon_ml_tpu.serving.batcher import request_from_json
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.serving.frontend.admission import (SHED_DRAINING,
                                                      SHED_NOT_READY,
                                                      SHED_SHUTDOWN,
                                                      AdmissionConfig,
                                                      AdmissionController)
from photon_ml_tpu.serving.frontend.fairness import FairQueue
from photon_ml_tpu.serving.frontend.protocol import (DEFAULT_MAX_LINE_BYTES,
                                                     BoundedLineReader,
                                                     LineTooLong, encode,
                                                     error_reply)
from photon_ml_tpu.serving.swap import HotSwapper

logger = logging.getLogger("photon_ml_tpu.serving.frontend")

_CLOSE = object()  # writer-task sentinel: flush backlog, then close


@dataclasses.dataclass(frozen=True)
class FrontendConfig:
    """Front-end policy knobs (wire format itself is not configurable)."""

    host: str = "127.0.0.1"
    port: int = 0  # 0 -> ephemeral; FrontendServer.port holds the binding
    max_line_bytes: int = DEFAULT_MAX_LINE_BYTES
    admission: AdmissionConfig = dataclasses.field(
        default_factory=AdmissionConfig)
    batcher_deadline_s: float = 500e-6
    flush_threshold: Optional[int] = None  # None -> engine's top bucket
    # max requests resident in the batcher at once; the rest wait in the
    # per-client fair queue where round-robin applies.  None -> 2 flush
    # waves: one scoring, one forming — enough to never starve the engine,
    # small enough that the backlog lives where fairness can see it.
    dispatch_window: Optional[int] = None
    drain_grace_s: float = 30.0
    predict_mean: bool = False
    # hard connection-count cap: excess accepts get ONE
    # {"error": "too_many_connections"} reply and a clean close, so a
    # connection storm cannot exhaust fds or per-conn task memory.
    # None = unlimited.
    max_connections: Optional[int] = None
    # shared secret: when set, the first line of every connection must be
    # {"cmd": "auth", "token": ...} (constant-time compare; one error
    # frame, then close).  None = open listener.
    auth_token: Optional[str] = None
    auth_timeout_s: float = 10.0
    # fleet tenancy: token -> tenant name.  A connection authenticating
    # with a tenant token is SCOPED to that tenant's models (requests for
    # another tenant's model get {"error": "forbidden"}); the global
    # auth_token (when also set) stays tenant-unscoped.  Setting this
    # turns the auth handshake on even without auth_token.
    tenant_tokens: Optional[Dict[str, str]] = None
    # sampled always-on tracing: when > 0 and the client sent no "tp",
    # mint a context for every Nth admitted request (deterministic
    # counter, photonpulse.maybe_mint) instead of every request — bounded
    # trace volume, but production flight dumps still carry request
    # context.  0 = mint for every request (the pre-fleet behavior).
    trace_sample_n: int = 0
    # /readyz-driven admission shedding: how often the HealthState (when
    # one is wired) is re-polled on the request path.  readyz walks every
    # check, so the throttle keeps it off the per-request cost.
    health_poll_s: float = 0.25
    # default CanaryPolicy knobs for {"cmd": "canary"} episodes (fields
    # the command itself carries win): fraction / min_observations /
    # max_drift
    canary_defaults: Optional[Dict[str, float]] = None


class _Conn:
    """Per-connection state: identity, streams, and the ordered reply
    queue its writer task drains.  ``tenant`` is set by a tenant-token
    auth handshake (None = unscoped)."""

    __slots__ = ("cid", "reader", "writer", "replies", "alive", "tenant")

    def __init__(self, cid: str, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.cid = cid
        self.reader = reader
        self.writer = writer
        self.replies: asyncio.Queue = asyncio.Queue()
        self.alive = True
        self.tenant: Optional[str] = None


class _Pending:
    """One admitted score request: reply future + settle-once accounting.
    ``t0_ns`` is the admission timestamp when tracing is on (None when
    off): settle records the enclosing ``front.request`` span from it.
    ``batcher``/``tenant`` are the fleet routing resolved at admission
    (None = the default single-engine batcher, untenanted)."""

    __slots__ = ("conn", "req", "reply", "settled", "t0_ns", "batcher",
                 "tenant")

    def __init__(self, conn: _Conn, req, reply: asyncio.Future,
                 t0_ns: Optional[int] = None, batcher=None,
                 tenant: Optional[str] = None):
        self.conn = conn
        self.req = req
        self.reply = reply
        self.settled = False
        self.t0_ns = t0_ns
        self.batcher = batcher
        self.tenant = tenant


class FrontendServer:
    """Asyncio TCP front end for one ScoringEngine (module docstring)."""

    def __init__(self, engine: ScoringEngine,
                 swapper: Optional[HotSwapper] = None,
                 config: Optional[FrontendConfig] = None,
                 registry=None, fleet=None, health=None):
        self.engine = engine
        self.swapper = swapper or HotSwapper(engine)
        self.config = config or FrontendConfig()
        self._registry = registry if registry is not None \
            else engine.metrics.registry
        # fleet mode: requests carry an optional "model" field routed to
        # per-model batchers; scoring goes through a FleetRouter so canary
        # episodes and shadow scorers can interpose per model.  None keeps
        # the single-engine edge byte-identical.
        self.fleet = fleet
        self.router = None
        self.health = health
        self._health_ok = True
        self._health_checked: Optional[float] = None
        if fleet is not None:
            from photon_ml_tpu.serving.fleet.router import FleetRouter
            self.router = FleetRouter(fleet, health=health)
        self._batchers: Dict[str, object] = {}  # model_id -> AsyncBatcher
        if self.router is not None and fleet.default_model is not None:
            self._batcher = self._model_batcher(fleet.default_model)
        else:
            self._batcher = engine.async_batcher(
                deadline_s=self.config.batcher_deadline_s,
                predict_mean=self.config.predict_mean,
                flush_threshold=self.config.flush_threshold)
        self._window = self.config.dispatch_window or \
            2 * self._batcher.flush_threshold
        self._tenant_inflight: Dict[str, int] = {}
        self._queue = FairQueue()
        self._admission = AdmissionController(self.config.admission,
                                              registry=self._registry)
        self._conns: Dict[str, _Conn] = {}
        # photonwatch subscriptions: delta-compression state is per
        # SUBSCRIBER, keyed by connection id (dropped with the connection)
        self._watch_exporters: Dict[str, object] = {}
        self._conn_seq = 0
        self._outstanding = 0  # resident in the batcher (dispatch window)
        self._inflight = 0     # admitted, not yet settled (drain barrier)
        self._draining = False
        # per-model drain barriers: models currently quiescing (their
        # requests shed; siblings keep serving), plus per-batcher
        # admitted-unsettled counts + idle events so a scoped drain can
        # wait on ONE model's batcher instead of the whole edge
        self._draining_models: set = set()
        self._batcher_inflight: Dict[int, int] = {}   # id(batcher) -> n
        self._batcher_idle: Dict[int, asyncio.Event] = {}
        # per-shard admission pressure: EWMA-ish share of recent admits
        # headed to each mesh shard (periodic halving keeps it recent)
        self._shard_counts: Dict[int, float] = {}
        self._shard_seen = 0.0
        self._closing = False
        self._server: Optional[asyncio.base_events.Server] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._state_lock: Optional[asyncio.Lock] = None  # swap/delta serial
        self._idle: Optional[asyncio.Event] = None
        self._closed: Optional[asyncio.Event] = None
        self.port: Optional[int] = None

    @property
    def batcher(self):
        """The edge's (default-model) AsyncBatcher — chaos.health wires a
        watchdog to its worker thread."""
        return self._batcher

    def _model_batcher(self, model_id: str):
        """Fleet mode: one AsyncBatcher per model, scoring through the
        router so canary/shadow interpose.  Built on first use; every
        batcher shares the fleet's one metrics registry."""
        b = self._batchers.get(model_id)
        if b is None:
            from photon_ml_tpu.serving.batcher import AsyncBatcher
            handle = self.fleet.handle(model_id)

            def score(reqs, _mid=model_id):
                return self.router.score(_mid, reqs,
                                         predict_mean=self.config.predict_mean)

            b = AsyncBatcher(
                score,
                flush_threshold=(self.config.flush_threshold
                                 or handle.engine.batcher.max_batch),
                deadline_s=self.config.batcher_deadline_s,
                metrics=handle.engine.metrics)
            self._batchers[model_id] = b
        return b

    def _all_batchers(self):
        seen = {id(self._batcher): self._batcher}
        for b in self._batchers.values():
            seen[id(b)] = b
        return list(seen.values())

    def _health_ready(self) -> bool:
        """Cached /readyz poll (throttled; config.health_poll_s).  No
        HealthState wired -> always ready (the pre-chaos edge)."""
        if self.health is None:
            return True
        now = time.monotonic()
        if (self._health_checked is None
                or now - self._health_checked >= self.config.health_poll_s):
            self._health_ok = bool(self.health.readyz()[0])
            self._health_checked = now
        return self._health_ok

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> "FrontendServer":
        self._loop = asyncio.get_running_loop()
        self._state_lock = asyncio.Lock()
        self._idle = asyncio.Event()
        self._idle.set()
        self._closed = asyncio.Event()
        self._server = await asyncio.start_server(
            self._on_connect, self.config.host, self.config.port)
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("photonfront listening on %s:%d (window %d, budget "
                    "%.1fms)", self.config.host, self.port, self._window,
                    self.config.admission.budget_s * 1e3)
        return self

    async def wait_closed(self) -> None:
        await self._closed.wait()

    async def aclose(self) -> None:
        """Graceful shutdown: stop accepting, drain in-flight work to
        completion, stop the batcher, close connections.  Idempotent."""
        if self._closing:
            await self._closed.wait()
            return
        self._closing = True
        if self._server is not None:
            self._server.close()
        async with self._state_lock:
            self._draining = True
            await self._drain()
        # batcher.shutdown joins its worker thread — off the loop
        for b in self._all_batchers():
            await self._loop.run_in_executor(
                None, lambda _b=b: _b.shutdown(drain=True))
        for conn in list(self._conns.values()):
            conn.replies.put_nowait(_CLOSE)
        if self._server is not None:
            await self._server.wait_closed()
        self._closed.set()

    # -- connection handling -----------------------------------------------
    async def _on_connect(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        act = _chaos_fault("front.conn")
        if act is not None:
            # chaos: the edge kills the connection before reading a byte —
            # nothing was admitted, so nothing can be lost; the client
            # retries against a fresh connection
            try:
                writer.close()
            except Exception:
                pass
            return
        cap = self.config.max_connections
        if cap is not None and len(self._conns) >= cap:
            self._registry.inc("front_connections_refused_total")
            obs_instant("front.refuse", connections=len(self._conns))
            try:
                writer.write(encode(
                    error_reply("too_many_connections", max_connections=cap)))
                await writer.drain()
            except (ConnectionError, OSError):
                pass
            finally:
                try:
                    writer.close()
                except Exception:
                    pass
            return
        with obs_span("front.accept"):
            peer = writer.get_extra_info("peername") or ("?", 0)
            self._conn_seq += 1
            cid = f"{peer[0]}:{peer[1]}#{self._conn_seq}"
            conn = _Conn(cid, reader, writer)
            self._conns[cid] = conn
            self._registry.inc("front_connections_total")
            self._registry.set_gauge("front_connections", len(self._conns))
        writer_task = asyncio.ensure_future(self._conn_writer(conn))
        try:
            await self._conn_reader(conn)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # abrupt disconnect: same cleanup as EOF
        finally:
            conn.alive = False
            self._abort_queued(conn)
            conn.replies.put_nowait(_CLOSE)
            try:
                await writer_task
            except asyncio.CancelledError:
                pass
            self._conns.pop(cid, None)
            self._watch_exporters.pop(cid, None)
            self._admission.forget_client(cid)
            self._registry.set_gauge("front_connections", len(self._conns))
            self._registry.set_gauge("front_queue_depth", 0, client=cid)

    def _match_token(self, token: str) -> Tuple[bool, Optional[str]]:
        """(accepted, tenant): the global token admits unscoped; a tenant
        token admits scoped to its tenant.  EVERY candidate is compared
        (constant-time each) so which one matched is not timeable."""
        ok, tenant = False, None
        tok = token.encode("utf-8")
        if self.config.auth_token is not None and hmac.compare_digest(
                tok, self.config.auth_token.encode("utf-8")):
            ok = True
        for cand, t in (self.config.tenant_tokens or {}).items():
            if hmac.compare_digest(tok, cand.encode("utf-8")) and not ok:
                ok, tenant = True, t
        return ok, tenant

    async def _authenticate(self, conn: _Conn,
                            lines: BoundedLineReader) -> bool:
        """First-line shared-secret handshake.  Anything but a good token
        — wrong secret, malformed line, oversize, timeout — costs exactly
        one ``{"error": "unauthorized"}`` frame and the connection.  A
        tenant token scopes the connection to that tenant's models."""
        try:
            raw = await asyncio.wait_for(lines.readline(),
                                         self.config.auth_timeout_s)
        except (asyncio.TimeoutError, LineTooLong):
            raw = None
        token = ""
        if raw is not None:
            try:
                obj = json.loads(raw)
            except ValueError:
                obj = None
            if isinstance(obj, dict) and obj.get("cmd") == "auth" and \
                    isinstance(obj.get("token"), str):
                token = obj["token"]
        ok, tenant = self._match_token(token)
        if not ok:
            self._registry.inc("front_auth_failures_total")
            obs_instant("front.auth_fail", client=conn.cid)
            logger.warning("photonfront: rejected unauthenticated "
                           "connection %s", conn.cid)
            self._reply_now(conn, error_reply("unauthorized"))
            return False
        conn.tenant = tenant
        reply = {"auth": "ok"}
        if tenant is not None:
            reply["tenant"] = tenant
        self._reply_now(conn, reply)
        return True

    async def _conn_reader(self, conn: _Conn) -> None:
        lines = BoundedLineReader(conn.reader.read,
                                  self.config.max_line_bytes)
        if self.config.auth_token is not None or self.config.tenant_tokens:
            if not await self._authenticate(conn, lines):
                return
        while True:
            try:
                raw = await lines.readline()
            except LineTooLong as e:
                self._registry.inc("front_protocol_errors_total",
                                   kind="oversize")
                self._reply_now(conn, error_reply(str(e)))
                continue
            if raw is None:
                return  # EOF
            line = raw.strip()
            if not line:
                self._flush_conn(conn)  # blank line: force-flush (stdio
                continue                # parity, scoped to this client)
            try:
                obj = json.loads(line)
            except ValueError as e:
                self._registry.inc("front_protocol_errors_total",
                                   kind="json")
                self._reply_now(conn, error_reply(str(e)))
                continue
            cmd = obj.get("cmd") if isinstance(obj, dict) else None
            if cmd is not None:
                await self._handle_cmd(conn, cmd, obj)
            elif isinstance(obj, dict):
                self._handle_request(conn, obj)
            else:
                self._registry.inc("front_protocol_errors_total",
                                   kind="json")
                self._reply_now(conn, error_reply(
                    f"expected a JSON object, got {type(obj).__name__}"))

    async def _conn_writer(self, conn: _Conn) -> None:
        """Drain the reply queue in order; replies may be dicts, futures of
        dicts, or zero-arg callables evaluated at WRITE time (metrics/trace
        snapshots must reflect everything already replied to)."""
        try:
            while True:
                entry = await conn.replies.get()
                if entry is _CLOSE:
                    return
                if asyncio.isfuture(entry):
                    try:
                        entry = await entry
                    except asyncio.CancelledError:
                        continue
                if callable(entry):
                    entry = entry()
                if entry is None:
                    continue
                conn.writer.write(encode(entry))
                await conn.writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass  # peer gone: stop writing, reader cleanup owns state
        finally:
            try:
                conn.writer.close()
            except Exception:
                pass

    # -- reply plumbing ----------------------------------------------------
    def _reply_now(self, conn: _Conn, obj: dict) -> None:
        conn.replies.put_nowait(obj)

    def _reply_future(self, conn: _Conn) -> asyncio.Future:
        fut = self._loop.create_future()
        conn.replies.put_nowait(fut)
        return fut

    # -- score-request path ------------------------------------------------
    def _resolve_fleet(self, conn: _Conn, req):
        """Fleet routing at admission: (handle, batcher, error_reply).
        ``None`` model -> the default handle, so pre-fleet clients work
        unchanged; an unknown model or a tenant-scope violation is an
        explicit error reply, never a shed (it would never succeed on
        retry)."""
        from photon_ml_tpu.serving.fleet.registry import UnknownModelError
        try:
            handle = self.fleet.resolve(req.model)
        except UnknownModelError:
            self._registry.inc("fleet_unknown_model_total")
            return None, None, error_reply("unknown_model", uid=req.uid,
                                           model=req.model)
        if conn.tenant is not None and handle.tenant != conn.tenant:
            self._registry.inc("fleet_forbidden_total", tenant=conn.tenant)
            return None, None, error_reply("forbidden", uid=req.uid,
                                           model=handle.model_id)
        return handle, self._model_batcher(handle.model_id), None

    def _handle_request(self, conn: _Conn, obj: dict) -> None:
        try:
            req = request_from_json(obj)
        except (ValueError, TypeError) as e:
            self._registry.inc("front_protocol_errors_total", kind="request")
            self._reply_now(conn, error_reply(str(e), uid=obj.get("uid")))
            return
        self._registry.inc("front_requests_total")
        handle, batcher, tenant = None, self._batcher, None
        if self.fleet is not None:
            handle, batcher, err = self._resolve_fleet(conn, req)
            if err is not None:
                self._reply_now(conn, err)
                return
            tenant = handle.tenant
        if self._draining or self._closing:
            self._shed(conn, req,
                       SHED_SHUTDOWN if self._closing else SHED_DRAINING,
                       self.config.admission.budget_s)
            return
        if handle is not None and handle.model_id in self._draining_models:
            # scoped barrier: only THIS model is quiescing (sibling
            # models keep admitting through their own batchers)
            self._shed(conn, req, SHED_DRAINING,
                       self.config.admission.budget_s)
            return
        if not self._health_ready():
            # /readyz-driven shedding: a not-ready plane (stalled worker,
            # stale catch-up, failed check) refuses work up front — the
            # client retries against a sibling instead of queueing here
            self._shed(conn, req, SHED_NOT_READY,
                       self.config.admission.budget_s)
            return
        estimate = batcher.queue_wait_estimate(extra=self._queue.depth())
        if self.config.admission.client_budget_s is not None:
            # the wait THIS client's own backlog explains: its fair-queue
            # depth over the shared batcher residue (other clients' queued
            # work is excluded — round-robin keeps it from billing here)
            client_wait = batcher.queue_wait_estimate(
                extra=self._queue.depth_of(conn.cid))
        else:
            client_wait = 0.0
        if (self.config.admission.tenant_budget_s is not None
                and tenant is not None):
            # the tenant's own backlog: its admitted-unsettled requests
            # over the model batcher's residue
            tenant_wait = batcher.queue_wait_estimate(
                extra=self._tenant_inflight.get(tenant, 0))
        else:
            tenant_wait = 0.0
        if self.config.admission.shard_budget_s is not None:
            shard, shard_wait = self._shard_pressure(handle, req, estimate)
        else:
            shard, shard_wait = None, 0.0
        verdict = self._admission.decide(
            estimate,
            client=conn.cid if self.config.admission.client_budget_s
            is not None else None,
            client_wait_s=client_wait,
            tenant=tenant, tenant_wait_s=tenant_wait,
            shard=shard, shard_wait_s=shard_wait)
        if not verdict.admitted:
            self._shed(conn, req, verdict.reason, verdict.predicted_wait_s,
                       verdict.retry_after_ms)
            return
        t0_ns = None
        if obs_enabled():
            # the propagation edge: adopt the context the request carried
            # on the wire ("tp", already parsed — garbage degraded to
            # None), or mint here at admission — every request, or every
            # Nth with sampled tracing (trace_sample_n); an unsampled
            # request proceeds untraced
            if req.ctx is None:
                req.ctx = ctx_maybe_mint(self.config.trace_sample_n) \
                    if self.config.trace_sample_n > 0 else ctx_mint()
            t0_ns = time.perf_counter_ns()
            with ctx_bind(req.ctx):
                obs_instant("front.admit", uid=req.uid, client=conn.cid,
                            predicted_wait_us=int(estimate * 1e6))
        if handle is not None:
            # per-tenant metric labels end to end: the admit is attributed
            # to its (model, tenant) pair
            self.engine.metrics.observe_fleet_request(handle.model_id,
                                                      tenant)
            self._tenant_inflight[tenant] = \
                self._tenant_inflight.get(tenant, 0) + 1
        self._inflight += 1
        self._idle.clear()
        self._track_admit(batcher)
        pending = _Pending(conn, req, self._reply_future(conn), t0_ns,
                           batcher=batcher, tenant=tenant)
        self._queue.enqueue(conn.cid, pending)
        self._registry.set_gauge("front_queue_depth",
                                 self._queue.depth_of(conn.cid),
                                 client=conn.cid)
        self._pump()

    def _shard_pressure(self, handle, req, estimate: float):
        """(shard, predicted wait) attributable to the mesh shard this
        request's hot-path work routes to — the admission signal for
        ``shard_budget_s``.  The wait model is the global backlog estimate
        scaled by the shard's share of recent admits times the shard
        count: uniform traffic gives every shard exactly the global
        estimate, a shard drawing k× its fair share shows k× the
        pressure.  Returns (None, 0.0) when the request has no shard
        affinity (unsharded store, unknown entity)."""
        engine = handle.engine if handle is not None else self.engine
        store = engine.store
        n = store.config.mesh_shards
        if n <= 1:
            return None, 0.0
        shard = store.shard_of_request(req.ids)
        if shard < 0:
            return None, 0.0
        self._shard_counts[shard] = self._shard_counts.get(shard, 0.0) + 1.0
        self._shard_seen += 1.0
        if self._shard_seen >= 512.0:  # halve: keep the share RECENT
            self._shard_counts = {s: c * 0.5
                                  for s, c in self._shard_counts.items()}
            self._shard_seen *= 0.5
        share = self._shard_counts[shard] / self._shard_seen
        wait = estimate * share * n
        engine.metrics.set_shard_pressure(shard, wait)
        return shard, wait

    def _track_admit(self, batcher) -> None:
        """Per-batcher admitted-unsettled count (scoped drain barrier)."""
        bid = id(batcher)
        self._batcher_inflight[bid] = self._batcher_inflight.get(bid, 0) + 1
        ev = self._batcher_idle.get(bid)
        if ev is None:
            ev = self._batcher_idle[bid] = asyncio.Event()
        ev.clear()

    def _track_settle(self, batcher) -> None:
        bid = id(batcher)
        left = self._batcher_inflight.get(bid, 1) - 1
        if left > 0:
            self._batcher_inflight[bid] = left
        else:
            self._batcher_inflight.pop(bid, None)
            ev = self._batcher_idle.get(bid)
            if ev is not None:
                ev.set()

    def _shed(self, conn: _Conn, req, reason: str, predicted_wait_s: float,
              retry_after_ms: Optional[float] = None) -> None:
        obs_instant("front.shed", uid=req.uid, client=conn.cid,
                    reason=reason)
        self._registry.inc("requests_shed_total", reason=reason)
        if retry_after_ms is None:
            retry_after_ms = self._admission.retry_after_ms(predicted_wait_s)
        self._reply_now(conn, {
            "uid": req.uid, "error": "overloaded", "reason": reason,
            "retry_after_ms": retry_after_ms,
            "predicted_wait_ms": round(predicted_wait_s * 1e3, 3)})

    def _pump(self) -> None:
        """Fill the dispatch window round-robin from the fair queue."""
        while self._outstanding < self._window:
            nxt = self._queue.next_item()
            if nxt is None:
                return
            cid, pending = nxt
            self._registry.set_gauge("front_queue_depth",
                                     self._queue.depth_of(cid), client=cid)
            self._dispatch(pending)

    def _dispatch(self, pending: _Pending) -> None:
        if pending.settled:
            return  # aborted while queued (connection died)
        try:
            fut = (pending.batcher or self._batcher).submit(pending.req)
        except RuntimeError as e:  # batcher already shut down
            self._settle(pending, error_reply(str(e), uid=pending.req.uid))
            return
        self._outstanding += 1
        fut.add_done_callback(
            lambda f: self._loop.call_soon_threadsafe(self._scored,
                                                      pending, f))

    def _scored(self, pending: _Pending, fut) -> None:
        self._outstanding -= 1
        if fut.cancelled():
            reply = error_reply("request cancelled at shutdown",
                                uid=pending.req.uid)
        else:
            exc = fut.exception()
            if exc is not None:
                reply = error_reply(str(exc), uid=pending.req.uid)
            else:
                # photonlint: disable=blocking-in-async -- `_scored` is
                # scheduled from the future's OWN done-callback, so the
                # future is already settled here and result() returns
                # without blocking
                reply = {"uid": pending.req.uid, "score": fut.result()}
        self._settle(pending, reply)
        self._pump()

    def _settle(self, pending: _Pending, reply: Optional[dict]) -> None:
        if pending.settled:
            return
        pending.settled = True
        if pending.t0_ns is not None:
            # explicit-timing span: admission and settle happen on
            # different event-loop ticks, so no `with` block can bracket
            # the request — this is the span that ENCLOSES the engine
            # flush on the merged timeline
            tracer = get_tracer()
            if tracer.enabled:
                with ctx_bind(pending.req.ctx):
                    tracer.complete(
                        "front.request", pending.t0_ns,
                        time.perf_counter_ns() - pending.t0_ns,
                        uid=pending.req.uid, client=pending.conn.cid)
        self._inflight -= 1
        self._track_settle(pending.batcher or self._batcher)
        if pending.tenant is not None:
            left = self._tenant_inflight.get(pending.tenant, 1) - 1
            if left > 0:
                self._tenant_inflight[pending.tenant] = left
            else:
                self._tenant_inflight.pop(pending.tenant, None)
        if self._inflight == 0:
            self._idle.set()
        if not pending.reply.done():
            pending.reply.set_result(reply)

    def _abort_queued(self, conn: _Conn) -> None:
        """Connection died: settle its queued-but-undispatched requests
        (dispatched ones resolve through the batcher as usual)."""
        for pending in self._queue.drop_client(conn.cid):
            self._settle(pending, None)

    def _flush_conn(self, conn: _Conn) -> None:
        """Blank-line semantics, scoped: THIS connection's queued requests
        go to the batcher now (ignoring the window) and the batcher
        flushes.  Other clients' backlogs stay in the fair queue — one
        client's flush must not launder another's firehose past the
        round-robin dispatcher."""
        for pending in self._queue.drop_client(conn.cid):
            self._dispatch(pending)
        self._registry.set_gauge("front_queue_depth", 0, client=conn.cid)
        for b in self._all_batchers():
            b.flush()

    def _flush_all(self) -> None:
        """Drain semantics: everything queued, every client, goes to its
        batcher now (ignoring the window) and every batcher flushes."""
        while True:
            nxt = self._queue.next_item()
            if nxt is None:
                break
            self._dispatch(nxt[1])
        for b in self._all_batchers():
            b.flush()

    # -- drain / control commands ------------------------------------------
    async def _drain(self) -> None:
        """Submit everything queued, flush, and wait until every admitted
        request has settled.  Callers hold ``_state_lock`` and have set
        ``_draining`` (so admission refuses new work meanwhile)."""
        with obs_span("front.drain", inflight=self._inflight,
                      queued=self._queue.depth()):
            self._registry.inc("front_drains_total")
            self._flush_all()
            if self._inflight:
                try:
                    await asyncio.wait_for(self._idle.wait(),
                                           self.config.drain_grace_s)
                except asyncio.TimeoutError:
                    logger.warning(
                        "drain grace (%.1fs) expired with %d in flight",
                        self.config.drain_grace_s, self._inflight)

    async def _drain_model(self, model_id: str) -> None:
        """Scoped drain: submit only ``model_id``'s queued requests (the
        rest go back to the fair queue), flush ITS batcher, and wait until
        its admitted requests settle.  Callers hold ``_state_lock`` and
        have added the model to ``_draining_models``."""
        batcher = self._model_batcher(model_id)
        bid = id(batcher)
        with obs_span("front.drain_model", model=model_id,
                      inflight=self._batcher_inflight.get(bid, 0)):
            self._registry.inc("front_drains_total")
            requeue = []
            while True:
                nxt = self._queue.next_item()
                if nxt is None:
                    break
                cid, pending = nxt
                if pending.batcher is batcher:
                    self._dispatch(pending)
                else:
                    requeue.append((cid, pending))
            for cid, pending in requeue:  # per-client FIFO order preserved
                self._queue.enqueue(cid, pending)
            batcher.flush()
            if self._batcher_inflight.get(bid, 0):
                try:
                    await asyncio.wait_for(self._batcher_idle[bid].wait(),
                                           self.config.drain_grace_s)
                except asyncio.TimeoutError:
                    logger.warning(
                        "model %s drain grace (%.1fs) expired with %d in "
                        "flight", model_id, self.config.drain_grace_s,
                        self._batcher_inflight.get(bid, 0))

    async def _quiesced(self, fn, model_id: Optional[str] = None):
        """Run ``fn`` (blocking, in the executor) with admission stopped
        and zero requests in flight — the swap/delta barrier.  With a
        ``model_id`` (fleet mode), the barrier is SCOPED: only that
        model's admission pauses and only its batcher drains, so an
        untouched sibling model keeps serving straight through a
        neighbor's swap/canary/promote."""
        async with self._state_lock:
            if model_id is None or self.fleet is None:
                self._draining = True
                try:
                    await self._drain()
                    return await self._loop.run_in_executor(None, fn)
                finally:
                    self._draining = False
            self._draining_models.add(model_id)
            try:
                await self._drain_model(model_id)
                return await self._loop.run_in_executor(None, fn)
            finally:
                self._draining_models.discard(model_id)

    def _cmd_target(self, obj: dict):
        """(swapper, store, model_id) a control command acts on: in fleet
        mode the optional ``"model"`` field routes to that handle
        (``UnknownModelError`` propagates to the caller's error reply);
        without a fleet, the single engine — byte-identical pre-fleet."""
        if self.fleet is not None:
            h = self.fleet.resolve(obj.get("model"))
            return h.swapper, h.engine.store, h.model_id
        return self.swapper, self.engine.store, None

    def _canary_policy(self, obj: dict):
        from photon_ml_tpu.serving.fleet.policy import CanaryPolicy
        kw = dict(self.config.canary_defaults or {})
        if obj.get("fraction") is not None:
            kw["fraction"] = float(obj["fraction"])
        if obj.get("min_observations") is not None:
            kw["min_observations"] = int(obj["min_observations"])
        if obj.get("max_drift") is not None:
            kw["max_drift"] = float(obj["max_drift"])
        return CanaryPolicy(**kw)

    def _load_store(self, model_dir: str, config):
        """Blocking (executor-side) bundle load for canary/shadow legs —
        built on the handle's own StoreConfig so the signature (and
        therefore the warmed executables) is shared with the active
        generation."""
        from photon_ml_tpu.serving.coefficient_store import CoefficientStore
        from photon_ml_tpu.storage.model_io import load_model_bundle
        bundle = load_model_bundle(model_dir)
        return CoefficientStore.from_bundle(bundle, config=config,
                                            version=model_dir,
                                            metrics=self.engine.metrics)

    async def _handle_cmd(self, conn: _Conn, cmd: str, obj: dict) -> None:
        if cmd == "swap":
            model_dir = obj.get("model_dir")
            if not model_dir:
                self._reply_now(conn, error_reply("swap needs model_dir"))
                return
            try:
                swapper, store, _mid = self._cmd_target(obj)
            except ValueError as e:
                self._reply_now(conn, error_reply(str(e)))
                return
            fut = self._reply_future(conn)
            ok = await self._quiesced(lambda: swapper.swap(model_dir),
                                      model_id=_mid)
            fut.set_result({
                "swap": "ok" if ok else "rejected",
                "generation": swapper.engine.store.generation,
                "version": swapper.engine.store.version,
                "delta_version": swapper.delta_version})
        elif cmd == "delta":
            try:
                swapper, store, _mid = self._cmd_target(obj)
            except ValueError as e:
                self._reply_now(conn, error_reply(str(e)))
                return
            fut = self._reply_future(conn)
            ok = await self._quiesced(
                lambda: swapper.apply_delta(obj.get("coordinate"),
                                            obj.get("entity"),
                                            obj.get("row") or ()),
                model_id=_mid)
            fut.set_result({"delta": "ok" if ok else "rejected",
                            "delta_version": swapper.delta_version})
        elif cmd == "rebalance":
            fut = self._reply_future(conn)
            if self.fleet is not None and obj.get("model") is None:
                # fleet-wide pass: every model, then the tenant-quota
                # invariant re-check + gauge export
                moves = await self._loop.run_in_executor(
                    None, self.fleet.rebalance)
                fut.set_result({"rebalance": {
                    mid: {cid: list(m) for cid, m in mm.items()}
                    for mid, mm in moves.items()}})
                return
            try:
                _swapper, store, _mid = self._cmd_target(obj)
            except ValueError as e:
                fut.set_result(error_reply(str(e)))
                return
            moves = await self._loop.run_in_executor(None, store.rebalance)
            fut.set_result({"rebalance": {cid: list(m)
                                          for cid, m in moves.items()}})
        elif cmd == "fleet":
            if self.router is None:
                self._reply_now(conn, error_reply(
                    "no fleet configured; run with --add-model"))
            else:
                self._reply_now(conn,
                                lambda: {"fleet": self.router.status()})
        elif cmd == "canary":
            if self.router is None:
                self._reply_now(conn, error_reply(
                    "no fleet configured; run with --add-model"))
                return
            model_dir = obj.get("model_dir")
            if not model_dir:
                self._reply_now(conn, error_reply("canary needs model_dir"))
                return
            try:
                handle = self.fleet.resolve(obj.get("model"))
                policy = self._canary_policy(obj)
            except ValueError as e:
                self._reply_now(conn, error_reply(str(e)))
                return
            fut = self._reply_future(conn)

            def _start():
                candidate = self._load_store(model_dir,
                                             handle.store.config)
                ctl = self.router.start_canary(handle.model_id, candidate,
                                               policy=policy,
                                               model_dir=model_dir)
                return ctl.status()

            try:
                status = await self._quiesced(_start,
                                              model_id=handle.model_id)
            except Exception as e:
                fut.set_result(error_reply(str(e)))
                return
            fut.set_result({"canary": status})
        elif cmd in ("promote", "rollback"):
            if self.router is None:
                self._reply_now(conn, error_reply(
                    "no fleet configured; run with --add-model"))
                return
            try:
                handle = self.fleet.resolve(obj.get("model"))
            except ValueError as e:
                self._reply_now(conn, error_reply(str(e)))
                return
            fut = self._reply_future(conn)

            def _ctl(_cmd=cmd, _mid=handle.model_id):
                if _cmd == "promote":
                    return self.router.promote(_mid).status()
                return self.router.rollback(
                    _mid, reason=obj.get("reason", "operator")).status()

            try:
                status = await self._quiesced(_ctl,
                                              model_id=handle.model_id)
            except ValueError as e:
                fut.set_result(error_reply(str(e)))
                return
            fut.set_result({cmd: status})
        elif cmd == "shadow":
            if self.router is None:
                self._reply_now(conn, error_reply(
                    "no fleet configured; run with --add-model"))
                return
            try:
                handle = self.fleet.resolve(obj.get("model"))
            except ValueError as e:
                self._reply_now(conn, error_reply(str(e)))
                return
            if obj.get("off"):
                fut = self._reply_future(conn)
                ok = await self._quiesced(
                    lambda: self.router.detach_shadow(handle.model_id),
                    model_id=handle.model_id)
                fut.set_result({"shadow": "off" if ok else "none",
                                "model": handle.model_id})
                return
            model_dir = obj.get("model_dir")
            if not model_dir:
                self._reply_now(conn, error_reply("shadow needs model_dir"))
                return
            fut = self._reply_future(conn)

            def _attach():
                store = self._load_store(model_dir, handle.store.config)
                self.router.attach_shadow(handle.model_id, store)
                return {"shadow": "on", "model": handle.model_id,
                        "version": store.version}

            try:
                reply = await self._quiesced(_attach,
                                             model_id=handle.model_id)
            except Exception as e:
                fut.set_result(error_reply(str(e)))
                return
            fut.set_result(reply)
        elif cmd == "metrics":
            # lazy: the snapshot is taken when the reply is WRITTEN, i.e.
            # after every earlier reply on this connection has resolved —
            # the stdio loop's flush-then-snapshot semantics
            for b in self._all_batchers():
                b.flush()
            if obj.get("format") == "prometheus":
                self._reply_now(conn, lambda: {
                    "prometheus": self.engine.metrics.to_prometheus()})
            else:
                self._reply_now(
                    conn, lambda: self.engine.metrics.snapshot())
        elif cmd == "trace":
            for b in self._all_batchers():
                b.flush()

            def _trace_reply():
                from photon_ml_tpu import obs

                tracer = obs.get_tracer()
                if not tracer.enabled:
                    return error_reply(
                        "tracing disabled; rerun with --trace")
                return tracer.chrome_trace()

            self._reply_now(conn, _trace_reply)
        elif cmd == "clock":
            # photonpulse ping-pong leg: t1 = receipt on our clock, t2 =
            # send time (lazy: stamped when the reply is actually written).
            # The caller combines them with its own t0/t3 to estimate the
            # offset between our perf_counter epochs (pulse.clock).
            t0 = obj.get("t0")
            t1 = pulse_clock.now_ns()
            who = get_process_label() or "frontend"
            self._reply_now(conn, lambda: {
                "clock": {"t0": t0, "t1": t1, "t2": pulse_clock.now_ns(),
                          "who": who}})
        elif cmd == "flight":
            recorder = get_flight()
            if recorder is None:
                self._reply_now(conn, error_reply(
                    "flight recorder not configured; rerun with "
                    "--flight-dir"))
            else:
                self._reply_now(conn,
                                lambda: {"flight": recorder.snapshot()})
        elif cmd == "watch":
            # photonwatch federation subscription: the first frame on a
            # connection is the full registry; every later ``watch`` gets
            # only the series that changed since (frames are lazy like
            # ``metrics``, snapshotted when the reply is written)
            for b in self._all_batchers():
                b.flush()
            exporter = self._watch_exporters.get(conn.cid)
            if exporter is None:
                from photon_ml_tpu.obs.watch import DeltaExporter
                exporter = self._watch_exporters[conn.cid] = DeltaExporter(
                    self._registry, label=get_process_label() or "frontend")
            self._reply_now(conn, lambda: {"watch": exporter.frame()})
        elif cmd == "shutdown":
            fut = self._reply_future(conn)
            fut.set_result({"shutdown": "ok",
                            "generation": self.engine.store.generation})
            asyncio.ensure_future(self.aclose())
        else:
            self._reply_now(conn, error_reply(f"unknown cmd {cmd!r}"))


class ThreadedFrontend:
    """Run a FrontendServer on a dedicated event-loop thread.

    The harness tests and the open-loop bench use: ``start()`` blocks until
    the socket is bound (``.port`` is then live), ``stop()`` runs the
    graceful drain and joins.  The CLI's asyncio main does NOT use this —
    it owns its loop; this exists for callers living in blocking code.
    """

    def __init__(self, engine: ScoringEngine,
                 swapper: Optional[HotSwapper] = None,
                 config: Optional[FrontendConfig] = None,
                 registry=None, fleet=None, health=None):
        self.server = FrontendServer(engine, swapper, config, registry,
                                     fleet=fleet, health=health)
        self._ready = threading.Event()
        self._error: Optional[BaseException] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="photonfront")

    @property
    def port(self) -> int:
        return self.server.port

    def _run(self) -> None:
        try:
            asyncio.run(self._main())
        except BaseException as e:  # startup failures surface in start()
            self._error = e
            self._ready.set()

    async def _main(self) -> None:
        try:
            await self.server.start()
        except BaseException as e:
            self._error = e
            self._ready.set()
            raise
        self._loop = asyncio.get_running_loop()
        self._ready.set()
        await self.server.wait_closed()

    def start(self, timeout: float = 30.0) -> "ThreadedFrontend":
        self._thread.start()
        if not self._ready.wait(timeout):
            raise RuntimeError("frontend did not start within "
                               f"{timeout}s")
        if self._error is not None:
            raise RuntimeError("frontend failed to start") from self._error
        return self

    def stop(self, timeout: float = 30.0) -> None:
        if self._loop is not None and self._thread.is_alive():
            asyncio.run_coroutine_threadsafe(self.server.aclose(),
                                             self._loop)
        self._thread.join(timeout)
