"""Localhost HTTP scrape endpoint for the serving metrics registry.

Photon ML reference counterpart: none — observability is infrastructure
the reference leaves outside the repo.  This closes the PR-5 follow-on:
``MetricsRegistry.to_prometheus()`` already renders the text exposition
format; what was missing is an HTTP listener a Prometheus scraper (or
``curl``) can hit.  Kept deliberately tiny — a hand-rolled asyncio
HTTP/1.0-style responder, not ``http.server`` — so it can ride on the SAME
event loop as the socket front end (one thread, one loop, no handler-class
plumbing), and so the stdio serve loop can host it on a sidecar thread via
:class:`ThreadedMetricsEndpoint` without dragging in a blocking server.

Routes:  ``GET /metrics`` -> Prometheus text exposition (or, for an
endpoint built with ``exemplars=True``, OpenMetrics 1.0.0 with photonpulse
trace-id exemplars on the histogram buckets — the content type flips to
``application/openmetrics-text`` so scrapers negotiate the richer parse);
``GET /metrics.json`` -> the structured JSON dump;
``GET /healthz`` -> 200 whenever this listener can answer at all (process
liveness); ``GET /readyz`` -> 200 when every registered readiness check
passes, 503 with the failing checks as JSON while degraded (orchestrator
traffic gate — see ``chaos/health.py``).  A sidecar built without a
``HealthState`` answers ``/readyz`` 200 vacuously, so a bare metrics
scraper deployment keeps working unchanged.  ``GET /flightz`` -> the
photonpulse flight recorder's spool index plus the latest degradation
dump (404 when no ``--flight-dir`` recorder is installed) — the same
payload the ``{"cmd": "flight"}`` wire command returns, reachable even
when the serving socket itself is what degraded.
``GET /watchz`` -> the photonwatch federation pull unit: the full
structured registry dump (labels structured, histograms as bucket counts)
wrapped with the process label and a timestamp — what a ``FleetView``
poller ingests; always a full state, never a delta (delta compression is
per-subscriber and lives on the ``{"cmd": "watch"}`` socket stream).
``GET /fleetz`` -> the merged fleet view with per-source staleness, served
only by an endpoint built with ``fleet_view=`` (the aggregator —
``tools/fleetwatch.py``); 404 elsewhere.  Anything else is 404.
Connections are one-shot (``Connection: close``) — scrape traffic, not an
API.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Optional

from photon_ml_tpu.chaos.health import HealthState
from photon_ml_tpu.serving.metrics import ServingMetrics

_MAX_REQUEST_BYTES = 8192  # a scrape request line + headers; hard bound


class MetricsEndpoint:
    """One-loop asyncio scrape listener (module docstring)."""

    def __init__(self, metrics: ServingMetrics, host: str = "127.0.0.1",
                 port: int = 0, health: Optional[HealthState] = None,
                 exemplars: bool = False, fleet_view=None):
        self.metrics = metrics
        self.host = host
        self.config_port = port
        self.health = health
        self.exemplars = exemplars
        self.fleet_view = fleet_view
        self.port: Optional[int] = None
        self._server: Optional[asyncio.base_events.Server] = None

    async def start(self) -> "MetricsEndpoint":
        self._server = await asyncio.start_server(self._handle, self.host,
                                                  self.config_port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def aclose(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            try:
                head = await asyncio.wait_for(
                    reader.readuntil(b"\r\n\r\n"), timeout=5.0)
            except asyncio.IncompleteReadError as e:
                head = e.partial  # curl -0 style or early close: best effort
            except (asyncio.LimitOverrunError, asyncio.TimeoutError):
                return
            if len(head) > _MAX_REQUEST_BYTES:
                writer.write(_response(431, b"request too large\n",
                                       b"text/plain"))
                return
            request_line = head.split(b"\r\n", 1)[0].split(b"\n", 1)[0]
            parts = request_line.split()
            method = parts[0].decode("latin-1") if parts else ""
            path = parts[1].decode("latin-1") if len(parts) > 1 else ""
            if method not in ("GET", "HEAD"):
                writer.write(_response(405, b"method not allowed\n",
                                       b"text/plain"))
                return
            status = 200
            if path in ("/metrics", "/metrics/"):
                if self.exemplars:
                    body = self.metrics.to_openmetrics().encode("utf-8")
                    ctype = (b"application/openmetrics-text; "
                             b"version=1.0.0; charset=utf-8")
                else:
                    body = self.metrics.to_prometheus().encode("utf-8")
                    ctype = b"text/plain; version=0.0.4; charset=utf-8"
            elif path == "/metrics.json":
                body = self.metrics.to_json().encode("utf-8")
                ctype = b"application/json"
            elif path == "/healthz":
                # liveness: answering at all IS the signal
                body = b'{"alive": true}\n'
                ctype = b"application/json"
            elif path == "/readyz":
                if self.health is None:
                    ready, checks = True, {}
                else:
                    # check evaluation can block (a pull check may take a
                    # lock a wedged worker holds) — keep the loop live
                    ready, checks = await asyncio.get_running_loop(
                        ).run_in_executor(None, self.health.readyz)
                status = 200 if ready else 503
                body = (json.dumps({"ready": ready, "checks": checks},
                                   sort_keys=True) + "\n").encode("utf-8")
                ctype = b"application/json"
            elif path == "/flightz":
                from photon_ml_tpu.obs.pulse import get_flight

                recorder = get_flight()
                if recorder is None:
                    writer.write(_response(
                        404, b"flight recorder not configured; rerun "
                             b"with --flight-dir\n", b"text/plain"))
                    return
                body = (json.dumps(recorder.snapshot(), sort_keys=True)
                        + "\n").encode("utf-8")
                ctype = b"application/json"
            elif path == "/watchz":
                body = (json.dumps(self.metrics.watch_state())
                        + "\n").encode("utf-8")
                ctype = b"application/json"
            elif path == "/fleetz":
                if self.fleet_view is None:
                    writer.write(_response(
                        404, b"no fleet view here; /fleetz is served by "
                             b"the aggregator (tools/fleetwatch.py)\n",
                        b"text/plain"))
                    return
                body = (json.dumps(self.fleet_view.fleet_snapshot(),
                                   sort_keys=True) + "\n").encode("utf-8")
                ctype = b"application/json"
            else:
                writer.write(_response(
                    404, b"try /metrics, /metrics.json, /healthz, /readyz, "
                         b"/flightz, /watchz or /fleetz\n", b"text/plain"))
                return
            writer.write(_response(status,
                                   b"" if method == "HEAD" else body,
                                   ctype, content_length=len(body)))
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass


_REASONS = {200: b"OK", 404: b"Not Found", 405: b"Method Not Allowed",
            431: b"Request Header Fields Too Large",
            503: b"Service Unavailable"}


def _response(status: int, body: bytes, ctype: bytes,
              content_length: Optional[int] = None) -> bytes:
    n = len(body) if content_length is None else content_length
    return (b"HTTP/1.0 %d %s\r\n"
            b"Content-Type: %s\r\n"
            b"Content-Length: %d\r\n"
            b"Connection: close\r\n\r\n"
            % (status, _REASONS.get(status, b"?"), ctype, n)) + body


class ThreadedMetricsEndpoint:
    """Run a MetricsEndpoint on its own event-loop thread — the sidecar
    the blocking stdio serve loop uses for ``--metrics-port``."""

    def __init__(self, metrics: ServingMetrics, host: str = "127.0.0.1",
                 port: int = 0, health: Optional[HealthState] = None,
                 exemplars: bool = False, fleet_view=None):
        self.endpoint = MetricsEndpoint(metrics, host, port, health=health,
                                        exemplars=exemplars,
                                        fleet_view=fleet_view)
        self._ready = threading.Event()
        self._stop: Optional[asyncio.Event] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="photonfront-metrics")

    @property
    def port(self) -> int:
        return self.endpoint.port

    def _run(self) -> None:
        try:
            asyncio.run(self._main())
        except BaseException as e:
            self._error = e
            self._ready.set()

    async def _main(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop = asyncio.Event()
        try:
            await self.endpoint.start()
        except BaseException as e:
            self._error = e
            self._ready.set()
            raise
        self._ready.set()
        await self._stop.wait()
        await self.endpoint.aclose()

    def start(self, timeout: float = 10.0) -> "ThreadedMetricsEndpoint":
        self._thread.start()
        if not self._ready.wait(timeout):
            raise RuntimeError("metrics endpoint did not start within "
                               f"{timeout}s")
        if self._error is not None:
            raise RuntimeError(
                "metrics endpoint failed to start") from self._error
        return self

    def stop(self, timeout: float = 10.0) -> None:
        if self._loop is not None and self._thread.is_alive():
            self._loop.call_soon_threadsafe(self._stop.set)
        self._thread.join(timeout)
