"""Admission control: deadline-budget load shedding with hysteresis.

Photon ML reference counterpart: none — overload behavior is the part of
LinkedIn's serving stack the paper leaves to infrastructure.  The policy
here is the classic one for a batching accelerator backend:

  **Shed when the work already admitted cannot resolve a new request
  within its deadline budget.**  The predictor is
  ``AsyncBatcher.queue_wait_estimate`` — an EWMA of observed flush
  latencies (the registry's ``serve.flush`` service times, observed where
  they happen) times the number of flush waves queued ahead, plus the
  residual deadline wait for a non-full tail bucket.  Under overload that
  estimate grows linearly with queue depth, so the controller starts
  refusing work while the queue is still ~one deadline deep — bounding
  p99 at roughly the budget instead of letting the queue (and every
  client's latency) grow without bound, which is exactly the cliff an
  open-loop arrival process exposes (``bench.py --serving --open-loop``).

  **Hysteresis makes shedding stable.**  A single threshold oscillates: one
  shed reply drains the queue below the limit, the next request is
  admitted, the queue refills, repeat — the shed/admit decision would
  flap at the arrival rate.  Instead the controller latches into a
  shedding state at the HIGH watermark (estimate > budget) and only
  unlatches at the LOW watermark (estimate <= ``resume_fraction`` *
  budget), so each transition requires the backlog to genuinely drain.

Shed replies carry ``retry_after_ms`` — the predicted time until the
backlog is back under the resume watermark, clamped to at least one
deadline budget — so a well-behaved client backs off instead of hammering.

  **Per-client budgets** (``client_budget_s``, off by default) add a
  second, narrower deadline checked FIRST against the wait attributable
  to the requesting client's OWN backlog (its fair-queue depth plus the
  shared batcher residue).  A single connection firehosing the edge trips
  its own latch (shed reason ``client_overload``) and gets refused while
  every other client keeps being admitted — without this, the burning
  client drives the GLOBAL estimate over budget and the edge latches shut
  for everyone.  Each client's latch carries the same two-watermark
  hysteresis; ``forget_client`` drops the latch when a connection closes.

  **Per-tenant budgets** (``tenant_budget_s``, off by default) generalize
  the per-client machinery one level up: a tenant is a *set* of
  connections serving one model family (serving/fleet), and its latch is
  checked against the wait attributable to that tenant's aggregate
  backlog.  Shed reason ``tenant_overload``; same hysteresis.  Unlike
  clients, tenant latches persist across connection churn — tenants are
  configured, not discovered — so there is no ``forget_tenant`` on close.

  **Per-shard budgets** (``shard_budget_s``, off by default) point the
  same machinery DOWN the stack: a shard is one slice of the pod-slice
  mesh, and the wait attributable to it is the frontend's estimate of the
  backlog headed for that shard (its share of recent traffic times the
  global estimate, scaled by the shard count — a hot shard's queue is the
  fleet p99 long before the average trips the global budget).  Shed
  reason ``shard_overload``; same two-watermark hysteresis, keyed by
  shard id.  The traffic-aware rebalance (serving/coefficient_store) is
  the slow corrective loop; this latch is the fast one that protects p99
  while placement catches up.

  **Readiness shedding** is the one check that is not a deadline: when the
  frontend's HealthState reports not-ready (``/readyz`` false), requests
  are refused up front with reason ``not_ready``.  The check lives in the
  frontend (it owns the HealthState); admission just names the reason so
  the shed metric and wire replies stay one vocabulary.

  **Fleet-pressure shedding** (``fleet_burn_budget``, off by default) is
  the photonwatch hook: the SLO engine publishes
  ``fleet_slo_burn_rate{slo=}`` gauges (into this process's registry in
  local mode, or pushed down from the fleet aggregator), and when the max
  across objectives exceeds the configured burn budget the edge sheds with
  reason ``fleet_pressure`` — skew visible only ACROSS frontends (every
  per-process estimate healthy, the fleet p99 burning) still gets load off
  the floor.  The gauge read is throttled (``fleet_burn_poll_s``) so the
  per-request cost is a float compare; the latch carries the same
  two-watermark hysteresis as every other shed reason.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional

from photon_ml_tpu.obs.pulse.flight import flight_dump
from photon_ml_tpu.obs.registry import MetricsRegistry

# requests_shed_total{reason=...} reasons
SHED_OVERLOAD = "overload"
SHED_DRAINING = "draining"
SHED_SHUTDOWN = "shutdown"
SHED_CLIENT = "client_overload"
SHED_TENANT = "tenant_overload"
SHED_SHARD = "shard_overload"
SHED_NOT_READY = "not_ready"
SHED_FLEET = "fleet_pressure"


@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """Knobs for the deadline-budget controller.

    ``budget_s``: per-request deadline — the latency the edge promises; a
    request predicted to resolve later than this is refused up front.
    ``resume_fraction``: the low watermark as a fraction of the budget
    (must sit strictly below 1.0 for the hysteresis to exist).
    ``retry_after_ms``: floor for the advisory backoff in shed replies.
    ``client_budget_s``: per-connection deadline checked against the
    client's OWN backlog wait (None = per-client budgets off; module
    docstring).  Usually set below ``budget_s`` so a burning client sheds
    before the whole edge latches.
    ``tenant_budget_s``: per-tenant deadline checked against the tenant's
    aggregate backlog wait (None = per-tenant budgets off) — one tenant's
    burst sheds under ``tenant_overload`` while other tenants' models keep
    admitting.
    ``shard_budget_s``: per-mesh-shard deadline checked against the wait
    attributable to the shard a request's hot-path work routes to (None =
    per-shard budgets off) — one overloaded slice sheds its own traffic
    under ``shard_overload`` instead of dragging the fleet p99.
    ``fleet_burn_budget``: max ``fleet_slo_burn_rate`` gauge value (across
    objectives) tolerated before shedding with reason ``fleet_pressure``
    (None = fleet-pressure shedding off; module docstring) — burn 1.0
    spends the error budget exactly on plan, so a sensible setting sits
    well above 1 (e.g. the SLO's page threshold).
    ``fleet_burn_poll_s``: how often the burn gauges are re-read; between
    polls ``decide`` compares against the cached value.
    """

    budget_s: float = 0.050
    resume_fraction: float = 0.5
    retry_after_ms: float = 0.0  # 0 -> derive from the budget
    client_budget_s: Optional[float] = None
    tenant_budget_s: Optional[float] = None
    shard_budget_s: Optional[float] = None
    fleet_burn_budget: Optional[float] = None
    fleet_burn_poll_s: float = 0.25

    def __post_init__(self):
        if self.budget_s <= 0:
            raise ValueError(f"budget_s must be > 0, got {self.budget_s}")
        if not 0.0 < self.resume_fraction < 1.0:
            raise ValueError("resume_fraction must be in (0, 1), got "
                             f"{self.resume_fraction}")
        if self.client_budget_s is not None and self.client_budget_s <= 0:
            raise ValueError("client_budget_s must be > 0, got "
                             f"{self.client_budget_s}")
        if self.tenant_budget_s is not None and self.tenant_budget_s <= 0:
            raise ValueError("tenant_budget_s must be > 0, got "
                             f"{self.tenant_budget_s}")
        if self.shard_budget_s is not None and self.shard_budget_s <= 0:
            raise ValueError("shard_budget_s must be > 0, got "
                             f"{self.shard_budget_s}")
        if self.fleet_burn_budget is not None and self.fleet_burn_budget <= 0:
            raise ValueError("fleet_burn_budget must be > 0, got "
                             f"{self.fleet_burn_budget}")
        if self.fleet_burn_poll_s <= 0:
            raise ValueError("fleet_burn_poll_s must be > 0, got "
                             f"{self.fleet_burn_poll_s}")


@dataclasses.dataclass
class Verdict:
    """One admission decision: ``admitted`` or shed with advice."""

    admitted: bool
    predicted_wait_s: float
    reason: Optional[str] = None  # SHED_* when not admitted
    retry_after_ms: float = 0.0


class AdmissionController:
    """Two-watermark (hysteresis) deadline-budget admission (module doc).

    Single-owner state: the front end calls ``decide`` from its event loop
    only, so the latch needs no lock — documented rather than defended,
    like the rest of the asyncio-side front-end state.
    """

    def __init__(self, config: Optional[AdmissionConfig] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.config = config or AdmissionConfig()
        self._registry = registry
        self._shedding = False
        self._client_shedding: Dict[str, bool] = {}  # latched clients only
        self._tenant_shedding: Dict[str, bool] = {}  # latched tenants only
        self._shard_shedding: Dict[int, bool] = {}   # latched shards only
        self._fleet_shedding = False
        self._fleet_burn = 0.0                 # cached gauge read
        self._fleet_burn_checked: Optional[float] = None

    @property
    def shedding(self) -> bool:
        return self._shedding

    def client_shedding(self, client: str) -> bool:
        return self._client_shedding.get(client, False)

    def tenant_shedding(self, tenant: str) -> bool:
        return self._tenant_shedding.get(tenant, False)

    def shard_shedding(self, shard: int) -> bool:
        return self._shard_shedding.get(shard, False)

    @property
    def fleet_shedding(self) -> bool:
        return self._fleet_shedding

    def _fleet_burn_now(self) -> float:
        """Max ``fleet_slo_burn_rate`` across objectives, re-read from the
        registry at most every ``fleet_burn_poll_s`` (the ``_health_ready``
        throttled-cache pattern) so per-request cost is a float compare."""
        now = time.monotonic()
        if (self._fleet_burn_checked is None
                or now - self._fleet_burn_checked
                >= self.config.fleet_burn_poll_s):
            series = self._registry.gauge_series("fleet_slo_burn_rate") \
                if self._registry is not None else {}
            self._fleet_burn = max(series.values(), default=0.0)
            self._fleet_burn_checked = now
        return self._fleet_burn

    def _set_fleet_shedding(self, value: bool) -> None:
        if value != self._fleet_shedding:
            self._fleet_shedding = value
            if self._registry is not None:
                self._registry.set_gauge("front_fleet_shedding", int(value))
            if value:
                # fleet latch ENGAGED: the burn the aggregator saw started
                # before this process shed — spool what this process has
                flight_dump("fleet_pressure", burn_rate=self._fleet_burn)

    def _set_shedding(self, value: bool) -> None:
        if value != self._shedding:
            self._shedding = value
            if self._registry is not None:
                self._registry.set_gauge("front_shedding", int(value))
            if value:
                # latch ENGAGED: the spans leading into overload are in
                # the ring right now — spool them before they get lapped
                # (one None check when no flight recorder is configured)
                flight_dump("admission_shed")

    def _set_client_shedding(self, client: str, value: bool) -> None:
        newly_latched = value and not self._client_shedding.get(client, False)
        if value:
            self._client_shedding[client] = True
        else:
            self._client_shedding.pop(client, None)
        if self._registry is not None:
            self._registry.set_gauge("front_client_shedding", int(value),
                                     client=client)
        if newly_latched:
            # per-client latch ENGAGED (edge-triggered, not per shed
            # reply): spool the flight ring so the burning client's spans
            # are retrievable from /flightz after the fact
            flight_dump("client_overload", client=client)

    def _set_tenant_shedding(self, tenant: str, value: bool) -> None:
        if value:
            self._tenant_shedding[tenant] = True
        else:
            self._tenant_shedding.pop(tenant, None)
        if self._registry is not None:
            self._registry.set_gauge("front_tenant_shedding", int(value),
                                     tenant=tenant)

    def _set_shard_shedding(self, shard: int, value: bool) -> None:
        if value:
            self._shard_shedding[shard] = True
        else:
            self._shard_shedding.pop(shard, None)
        if self._registry is not None:
            self._registry.set_gauge("front_shard_shedding", int(value),
                                     shard=str(shard))

    def forget_client(self, client: str) -> None:
        """Drop a closed connection's latch (and its gauge series)."""
        if client in self._client_shedding:
            self._set_client_shedding(client, False)

    def _retry_ms(self, predicted_wait_s: float, budget_s: float) -> float:
        c = self.config
        drain_s = max(predicted_wait_s - c.resume_fraction * budget_s, 0.0)
        return round(max(drain_s, budget_s, c.retry_after_ms * 1e-3) * 1e3,
                     3)

    def retry_after_ms(self, predicted_wait_s: float) -> float:
        """Advisory backoff: predicted time until the backlog is under the
        resume watermark, floored at one budget (a client that retries
        sooner than the backlog can possibly drain just re-queues itself
        for another shed reply)."""
        return self._retry_ms(predicted_wait_s, self.config.budget_s)

    def decide(self, predicted_wait_s: float,
               client: Optional[str] = None,
               client_wait_s: float = 0.0,
               tenant: Optional[str] = None,
               tenant_wait_s: float = 0.0,
               shard: Optional[int] = None,
               shard_wait_s: float = 0.0) -> Verdict:
        """One admission decision for a request arriving now, given the
        backlog predictor's estimate of its time-to-resolution and (with
        per-client/per-tenant/per-shard budgets on) the wait attributable
        to the requesting client's, tenant's, and target shard's own
        backlogs.  ``shard`` < 0 means the request has no shard affinity
        (unsharded store, cold entity) and skips the shard check."""
        c = self.config
        if c.client_budget_s is not None and client is not None:
            # the narrow check first: a client burning its own budget is
            # shed alone, BEFORE its backlog can trip the global latch
            budget = c.client_budget_s
            if self._client_shedding.get(client, False):
                if client_wait_s <= budget * c.resume_fraction:
                    self._set_client_shedding(client, False)
                else:
                    return Verdict(False, client_wait_s, SHED_CLIENT,
                                   self._retry_ms(client_wait_s, budget))
            elif client_wait_s > budget:
                self._set_client_shedding(client, True)
                return Verdict(False, client_wait_s, SHED_CLIENT,
                               self._retry_ms(client_wait_s, budget))
        if c.tenant_budget_s is not None and tenant is not None:
            # one level wider than a client, still narrower than global: a
            # tenant burst sheds under its own latch while other tenants'
            # models keep admitting
            budget = c.tenant_budget_s
            if self._tenant_shedding.get(tenant, False):
                if tenant_wait_s <= budget * c.resume_fraction:
                    self._set_tenant_shedding(tenant, False)
                else:
                    return Verdict(False, tenant_wait_s, SHED_TENANT,
                                   self._retry_ms(tenant_wait_s, budget))
            elif tenant_wait_s > budget:
                self._set_tenant_shedding(tenant, True)
                return Verdict(False, tenant_wait_s, SHED_TENANT,
                               self._retry_ms(tenant_wait_s, budget))
        if c.shard_budget_s is not None and shard is not None and shard >= 0:
            # narrower than global, orthogonal to client/tenant: one hot
            # mesh slice sheds ITS requests while the cool shards (and
            # shard-less traffic) keep admitting
            budget = c.shard_budget_s
            if self._shard_shedding.get(shard, False):
                if shard_wait_s <= budget * c.resume_fraction:
                    self._set_shard_shedding(shard, False)
                else:
                    return Verdict(False, shard_wait_s, SHED_SHARD,
                                   self._retry_ms(shard_wait_s, budget))
            elif shard_wait_s > budget:
                self._set_shard_shedding(shard, True)
                return Verdict(False, shard_wait_s, SHED_SHARD,
                               self._retry_ms(shard_wait_s, budget))
        if c.fleet_burn_budget is not None:
            # the widest check: the fleet aggregator's burn-rate gauges say
            # the WHOLE constellation is spending its error budget too fast
            # — shed here even though this process's own backlog is healthy
            burn = self._fleet_burn_now()
            if self._fleet_shedding:
                if burn <= c.fleet_burn_budget * c.resume_fraction:
                    self._set_fleet_shedding(False)
                else:
                    return Verdict(False, predicted_wait_s, SHED_FLEET,
                                   self.retry_after_ms(predicted_wait_s))
            elif burn > c.fleet_burn_budget:
                self._set_fleet_shedding(True)
                return Verdict(False, predicted_wait_s, SHED_FLEET,
                               self.retry_after_ms(predicted_wait_s))
        if self._shedding:
            if predicted_wait_s <= c.budget_s * c.resume_fraction:
                self._set_shedding(False)  # backlog drained: unlatch
            else:
                return Verdict(False, predicted_wait_s, SHED_OVERLOAD,
                               self.retry_after_ms(predicted_wait_s))
        elif predicted_wait_s > c.budget_s:
            self._set_shedding(True)  # latch: stays shedding until the
            # estimate is back under the LOW watermark, not just under the
            # budget — that gap is what keeps the decision from flapping
            return Verdict(False, predicted_wait_s, SHED_OVERLOAD,
                           self.retry_after_ms(predicted_wait_s))
        if self._registry is not None:
            self._registry.observe("front_predicted_wait_s",
                                   predicted_wait_s)
        return Verdict(True, predicted_wait_s)
