"""photonfront — the network serving edge (ROADMAP item 2).

A stdlib-only asyncio TCP front end multiplexing many concurrent client
connections into the AOT serving stack, speaking the same newline-
delimited JSON wire protocol as the stdio ``cli/serve.py`` loop:

  - ``protocol``: bounded line framing (one oversized/malformed line gets
    an error reply; the connection survives);
  - ``admission``: deadline-budget load shedding with hysteresis, fed by
    ``AsyncBatcher.queue_wait_estimate``;
  - ``fairness``: per-client round-robin queue draining;
  - ``server``: the :class:`FrontendServer` tying those together with
    graceful drain on swap/delta/shutdown/SIGTERM;
  - ``metrics_http``: the ``GET /metrics`` Prometheus scrape endpoint;
  - ``loadgen``: the open-loop Poisson generator behind
    ``bench.py --serving --open-loop``.

``cli/serve.py --listen host:port`` runs it; stdio stays the default.
"""

from photon_ml_tpu.serving.frontend.admission import (AdmissionConfig,  # noqa: F401
                                                      AdmissionController,
                                                      Verdict)
from photon_ml_tpu.serving.frontend.fairness import FairQueue  # noqa: F401
from photon_ml_tpu.serving.frontend.loadgen import (OpenLoopResult,  # noqa: F401
                                                    run_open_loop)
from photon_ml_tpu.serving.frontend.metrics_http import (  # noqa: F401
    MetricsEndpoint, ThreadedMetricsEndpoint)
from photon_ml_tpu.serving.frontend.protocol import (  # noqa: F401
    DEFAULT_MAX_LINE_BYTES, BoundedLineReader, LineTooLong,
    iter_bounded_lines)
from photon_ml_tpu.serving.frontend.server import (FrontendConfig,  # noqa: F401
                                                   FrontendServer,
                                                   ThreadedFrontend)
