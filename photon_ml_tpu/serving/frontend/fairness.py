"""Per-client fair queuing: round-robin draining across connection queues.

Photon ML reference counterpart: none (edge infrastructure).  The problem
is the standard one: with a single shared FIFO in front of the batcher, a
firehose client that submits 10k requests in one burst parks every other
client's requests behind its backlog — the trickle client's p99 becomes
the firehose's queue length.  Queuing PER CLIENT and draining ROUND-ROBIN
bounds any client's added wait by (clients x dispatch quantum), no matter
how deep another client's private queue grows.

Round-robin here is deficit-round-robin degenerated to quantum=1: every
request costs the same one batcher slot (the engine re-buckets internally),
so per-client deficit counters would all tick in lockstep — the plain
rotation IS DRR for unit-cost work.  If request costs ever diverge (e.g.
per-request batch scoring), this is the seam where deficits slot in.

Single-owner state: mutated only from the front end's event loop (enqueue
on read, drain on dispatch), so no lock — same discipline as the rest of
the asyncio-side state.
"""

from __future__ import annotations

import collections
from typing import Deque, Dict, Iterator, List, Optional, Tuple


class FairQueue:
    """Round-robin multiplexer over per-client FIFO queues (module doc).

    ``enqueue`` appends to a client's private FIFO (created on first use);
    ``next_item`` pops one item from the next client in the rotation;
    clients preserve FIFO order internally, so per-client submission order
    survives fair interleaving.  Empty clients leave the rotation
    automatically and re-enter at the tail on their next enqueue.
    """

    def __init__(self) -> None:
        self._queues: Dict[str, Deque] = {}
        self._rotation: Deque[str] = collections.deque()
        self._depth = 0

    def enqueue(self, client: str, item) -> None:
        q = self._queues.get(client)
        if q is None:
            q = self._queues[client] = collections.deque()
        if not q:
            self._rotation.append(client)  # (re-)enter at the tail
        q.append(item)
        self._depth += 1

    def next_item(self) -> Optional[Tuple[str, object]]:
        """Pop one (client, item) round-robin; None when empty."""
        while self._rotation:
            client = self._rotation.popleft()
            q = self._queues.get(client)
            if not q:
                continue  # drained via drop_client; fall through
            item = q.popleft()
            self._depth -= 1
            if q:
                self._rotation.append(client)  # still has work: rotate
            else:
                del self._queues[client]
            return client, item
        return None

    def drain(self) -> Iterator[Tuple[str, object]]:
        """Pop everything, round-robin order (graceful-drain path)."""
        while True:
            nxt = self.next_item()
            if nxt is None:
                return
            yield nxt

    def drop_client(self, client: str) -> List:
        """Remove a client's queued items (disconnect); returns them so the
        caller can resolve their reply futures."""
        q = self._queues.pop(client, None)
        if not q:
            return []
        self._depth -= len(q)
        # the rotation entry, if any, is lazily skipped by next_item
        return list(q)

    def depth(self) -> int:
        return self._depth

    def depth_of(self, client: str) -> int:
        q = self._queues.get(client)
        return len(q) if q else 0

    def clients(self) -> List[str]:
        return list(self._queues)
