"""Request micro-batcher with padding to a fixed bucket ladder.

Photon ML reference counterpart: the Spark GameTransformer scores whatever
partition sizes the RDD hands it — shape polymorphism is free on CPU.  On an
accelerator every new batch shape is a fresh XLA compile, so the online path
pads each micro-batch up to a SMALL FIXED LADDER of bucket sizes (the same
power-of-two idiom ``parallel/bucketing.py`` uses for per-entity sample
capacities) and every request shape lands on an already-compiled executable
(serving/engine.py).  Padded rows carry zero features and slot -1, so they
are inert through the scoring contraction and are sliced off before results
leave the engine.

Also home to the request schema: ``Request`` (parsed, array-ready) and
``request_from_json`` — the JSON-lines wire format of ``cli/serve.py``,
whose feature triples flow through the SAME (name, term) -> column mapping
``data/reader.read_game_data_avro`` applies to training records, so online
features land in exactly the training columns.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import (Callable, Dict, List, Optional, Sequence, Tuple)

import numpy as np

from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.obs.pulse.context import bind as ctx_bind
from photon_ml_tpu.obs.pulse.context import from_wire as ctx_from_wire
from photon_ml_tpu.obs.trace import enabled as obs_enabled
from photon_ml_tpu.obs.trace import instant as obs_instant
from photon_ml_tpu.obs.trace import span as obs_span


def pow2_bucket_ladder(max_batch: int, min_bucket: int = 1) -> Tuple[int, ...]:
    """1, 2, 4, ... up to (and including) the next power of two >= max_batch
    — the same rounding rule as ``parallel/bucketing._capacity_classes``."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    top = 1 << (max_batch - 1).bit_length()
    if min_bucket > top:
        # a ladder whose only rung is below min_bucket can't hold any batch
        # the caller promised to send — fail loudly instead of under-bucketing
        raise ValueError(
            f"min_bucket {min_bucket} exceeds the top bucket {top} implied "
            f"by max_batch {max_batch}")
    ladder = []
    b = max(1, min_bucket)
    while b < top:
        ladder.append(b)
        b <<= 1
    ladder.append(top)
    return tuple(ladder)


@dataclasses.dataclass
class Request:
    """One scoring request, array-ready.

    ``features``: ONE name/term/value triple list shared by every feature
    shard (exactly like a TrainingExampleAvro record — each shard's index
    map picks out the columns it knows).  ``ids``: id-tag -> entity string
    (reference GameDatum idTagToValueMap).  ``offset``: added to the raw
    margin, never part of the model score.  ``ctx``: optional photonpulse
    trace context — minted at the frontend edge or adopted from the wire
    ``"tp"`` field — carried with the request into the batcher so the
    flush that scores it joins the same cross-process trace.  ``model``:
    optional fleet model id (wire ``"model"`` field); ``None`` routes to
    the default model, which is what every pre-fleet client sends.
    """

    uid: object = None
    features: Sequence[dict] = ()
    ids: Dict[str, str] = dataclasses.field(default_factory=dict)
    offset: float = 0.0
    ctx: Optional[Tuple[str, str]] = None
    model: Optional[str] = None


def request_from_json(obj: dict) -> Request:
    """Wire JSON -> Request.  Accepts features as NTV triple dicts
    ([{"name": ..., "term": ..., "value": ...}, ...]) or compact
    [name, value] / [name, term, value] lists."""
    feats = []
    for f in obj.get("features") or ():
        if isinstance(f, dict):
            feats.append(f)
        elif isinstance(f, (list, tuple)) and len(f) == 2:
            feats.append({"name": f[0], "term": "", "value": f[1]})
        elif isinstance(f, (list, tuple)) and len(f) == 3:
            feats.append({"name": f[0], "term": f[1], "value": f[2]})
        else:
            raise ValueError(f"unparseable feature entry {f!r}")
    ids = {str(k): str(v) for k, v in (obj.get("ids") or {}).items()}
    # optional trace context: a malformed/torn "tp" decodes to None (the
    # request proceeds untraced); skipped entirely when tracing is off
    ctx = None
    tp = obj.get("tp")
    if tp is not None and obs_enabled():
        ctx = ctx_from_wire(tp)
    # optional fleet model id; absent -> None -> the default model, so
    # pre-fleet clients keep working unchanged
    model = obj.get("model")
    if model is not None:
        model = str(model)
    return Request(uid=obj.get("uid"), features=feats, ids=ids,
                   offset=float(obj.get("offset") or 0.0), ctx=ctx,
                   model=model)


def densify_features(requests: Sequence[Request], index_maps: Dict[str, IndexMap],
                     n_rows: int, dtype=np.float32) -> Dict[str, np.ndarray]:
    """Requests -> one padded dense [n_rows, d_shard] matrix per shard.

    Mirrors data/reader.read_game_data_avro's record loop exactly: intercept
    column filled with 1, features accumulated through
    ``IndexMap.get_index(name, term)``, unknown features dropped.  Rows
    beyond ``len(requests)`` stay all-zero (padding; inert through every
    scoring contraction).  Shards sharing one IndexMap object share ONE
    matrix (the reader's aliasing trick).
    """
    mats: Dict[str, np.ndarray] = {}
    by_map: Dict[int, np.ndarray] = {}
    for shard, m in index_maps.items():
        x = by_map.get(id(m))
        if x is None:
            x = np.zeros((n_rows, m.size), dtype)
            ii = m.intercept_index
            if ii is not None:
                x[: len(requests), ii] = 1.0
            for i, req in enumerate(requests):
                for feat in req.features:
                    j = m.get_index(feat["name"], feat.get("term") or "")
                    if j >= 0:
                        x[i, j] += feat["value"]
            by_map[id(m)] = x
        mats[shard] = x
    return mats


@dataclasses.dataclass(frozen=True)
class MicroBatch:
    """One planned launch: requests[start:stop] padded to ``bucket`` rows."""

    start: int
    stop: int
    bucket: int

    @property
    def real_rows(self) -> int:
        return self.stop - self.start


class BucketedBatcher:
    """Split a request stream into bucket-padded micro-batches.

    ``bucket_sizes``: the compiled-shape ladder (default: powers of two up
    to ``max_batch``).  A chunk of n requests pads to the smallest bucket
    >= n; streams longer than the top bucket split into top-bucket chunks
    first (full buckets have zero padding waste, so the tail is the only
    waste source — the padding-waste metric tracks it).
    """

    def __init__(self, max_batch: int = 64,
                 bucket_sizes: Optional[Sequence[int]] = None):
        if bucket_sizes is None:
            bucket_sizes = pow2_bucket_ladder(max_batch)
        sizes = sorted(set(int(b) for b in bucket_sizes))
        if not sizes or sizes[0] < 1:
            raise ValueError(f"invalid bucket sizes {bucket_sizes!r}")
        self.bucket_sizes: Tuple[int, ...] = tuple(sizes)
        self.max_batch = self.bucket_sizes[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (n must not exceed the top bucket)."""
        for b in self.bucket_sizes:
            if b >= n:
                return b
        raise ValueError(f"batch of {n} exceeds top bucket {self.max_batch}")

    def plan(self, n_requests: int) -> List[MicroBatch]:
        """Cut n requests into launches: full top-bucket chunks, then one
        padded tail chunk."""
        plan: List[MicroBatch] = []
        start = 0
        while start < n_requests:
            chunk = min(self.max_batch, n_requests - start)
            plan.append(MicroBatch(start=start, stop=start + chunk,
                                   bucket=self.bucket_for(chunk)))
            start += chunk
        return plan

    def padding_rows(self, plan: Sequence[MicroBatch]) -> int:
        return sum(mb.bucket - mb.real_rows for mb in plan)


class AsyncBatcher:
    """Thread-safe deadline-or-full micro-batch accumulator.

    The synchronous ``BucketedBatcher`` API makes the CALLER responsible for
    batch formation — at low QPS every caller hands over a near-singleton
    list and pays the pow2 ladder's padding tax (the 19.5% padding-waste
    ratio in BENCH_SERVING_cpu.json).  This accumulator inverts that:
    callers ``submit`` ONE request at a time and get a
    ``concurrent.futures.Future`` back; a worker thread flushes the pending
    set whenever it reaches ``flush_threshold`` (the engine's top bucket —
    a zero-padding launch) OR the OLDEST pending request has waited
    ``deadline_s`` (default 500µs), whichever comes first.  Concurrent
    low-QPS streams therefore coalesce into high-occupancy buckets, and no
    request waits longer than one deadline for company.

    ``score_fn`` receives the drained request list and returns one score
    per request (``ScoringEngine.score_requests`` — which still splits
    oversized drains along the bucket ladder); each future resolves to its
    request's float score, or to the scoring exception.

    Flush accounting (per-flush, into ``metrics`` when given):
    ``flushes_full`` (threshold reached), ``flushes_deadline`` (deadline
    expired first), ``flushes_forced`` (explicit ``flush()`` / shutdown
    drain) — the occupancy story of a deployment in one ratio.

    Introspection for admission control (serving/frontend): the worker
    keeps an EWMA of observed flush wall times and marks when a flush is in
    progress, so ``queue_wait_estimate`` can predict how long a request
    arriving NOW would wait — the in-flight flush's remainder, plus one
    EWMA per queued flush wave, plus the residual deadline if the tail wave
    would not fill.  All of it reads/writes under ``self._cond`` like every
    other batcher attribute.
    """

    _EWMA_ALPHA = 0.2  # flush-cost smoothing: ~5-flush memory

    def __init__(self, score_fn: Callable[[Sequence[Request]], np.ndarray],
                 flush_threshold: int,
                 deadline_s: float = 500e-6,
                 metrics=None,
                 name: str = "photon-serving-batcher"):
        if flush_threshold < 1:
            raise ValueError(
                f"flush_threshold must be >= 1, got {flush_threshold}")
        if deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {deadline_s}")
        self._score = score_fn
        self.flush_threshold = int(flush_threshold)
        self.deadline_s = float(deadline_s)
        self._metrics = metrics
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: List[Tuple[Request, Future]] = []
        self._first_ts: Optional[float] = None  # arrival of oldest pending
        self._force = False
        self._closed = False
        self._flush_ewma_s: Optional[float] = None  # observed flush cost
        self._inflight_since: Optional[float] = None  # flush in progress
        # optional chaos.health.WorkerWatch: wraps each flush so a
        # watchdog can flip readiness on a wedged scorer
        self.watch = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=name)
        self._thread.start()

    @property
    def worker_thread(self) -> threading.Thread:
        """The flush worker — what a chaos.health.Watchdog registers."""
        return self._thread

    # -- producer side -----------------------------------------------------
    def submit(self, request: Request) -> "Future[float]":
        """Enqueue one request; returns the future its score resolves on."""
        if request.ctx is not None:
            with ctx_bind(request.ctx):
                obs_instant("serve.submit", uid=request.uid)
        else:
            obs_instant("serve.submit", uid=request.uid)
        fut: Future = Future()
        with self._cond:
            if self._closed:
                raise RuntimeError("AsyncBatcher is shut down")
            self._pending.append((request, fut))
            if self._first_ts is None:
                self._first_ts = time.perf_counter()
            self._cond.notify()
        return fut

    def flush(self) -> List[Future]:
        """Force an immediate flush of whatever is pending; returns the
        pending futures (callers wait on those, not on this call)."""
        with self._cond:
            futs = [f for _, f in self._pending]
            if self._pending:
                self._force = True
                self._cond.notify()
        return futs

    def pending_count(self) -> int:
        """Requests accumulated and not yet handed to a flush."""
        with self._cond:
            return len(self._pending)

    def flush_cost_estimate(self) -> float:
        """EWMA of observed flush wall times (scoring one wave); the
        deadline is the optimistic floor until the first flush lands."""
        with self._cond:
            return self._flush_cost_locked()

    def _flush_cost_locked(self) -> float:
        # photonlint: disable=alias-escape -- returns a float (EWMA
        # sample); the _locked suffix is the calling convention: every
        # caller already holds self._cond
        return (self._flush_ewma_s if self._flush_ewma_s is not None
                else self.deadline_s)

    def queue_wait_estimate(self, extra: int = 0) -> float:
        """Predicted seconds until a request arriving NOW resolves, given
        ``extra`` requests queued ahead of it outside the batcher (the
        front end's fair queue).  The admission controller's input.

        Components: the in-flight flush's unfinished remainder; one flush
        cost per wave the backlog fills; the residual deadline wait when
        the tail wave would flush non-full.
        """
        with self._cond:
            now = time.perf_counter()
            ewma = self._flush_cost_locked()
            ahead = len(self._pending) + max(0, int(extra))
            est = 0.0
            if self._inflight_since is not None:
                est += max(0.0, ewma - (now - self._inflight_since))
            waves, tail = divmod(ahead + 1, self.flush_threshold)
            if tail:
                waves += 1
                est += self.deadline_s  # non-full tail waits out the clock
            est += waves * ewma
            return est

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop the worker.  ``drain=True`` scores everything still pending
        first (every outstanding future resolves); ``drain=False`` cancels
        pending futures.  Idempotent; ``submit`` raises afterwards."""
        with self._cond:
            if not self._closed:
                self._closed = True
                if not drain:
                    for _, f in self._pending:
                        f.cancel()
                    self._pending = []
                    self._first_ts = None
                self._cond.notify_all()
        self._thread.join(timeout)

    def __enter__(self) -> "AsyncBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=True)

    # -- worker side -------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if not self._pending:
                    return  # closed and drained
                deadline = self._first_ts + self.deadline_s
                while (not self._force and not self._closed
                       and len(self._pending) < self.flush_threshold):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                batch = self._pending
                self._pending = []
                self._first_ts = None
                forced, self._force = self._force, False
                closed = self._closed
                self._inflight_since = time.perf_counter()
            if self.watch is not None:
                with self.watch.busy():
                    self._flush_batch(batch, forced=forced or closed)
            else:
                self._flush_batch(batch, forced=forced or closed)
            with self._cond:
                dt = time.perf_counter() - self._inflight_since
                self._inflight_since = None
                prev = self._flush_ewma_s
                self._flush_ewma_s = dt if prev is None else (
                    (1.0 - self._EWMA_ALPHA) * prev + self._EWMA_ALPHA * dt)

    def _flush_batch(self, batch: List[Tuple[Request, Future]],
                     forced: bool) -> None:
        if not batch:
            return
        full = len(batch) >= self.flush_threshold
        if self._metrics is not None:
            self._metrics.inc("flushes_full" if full else
                              "flushes_forced" if forced else
                              "flushes_deadline")
        live = [(r, f) for r, f in batch if f.set_running_or_notify_cancel()]
        if not live:
            return
        attrs = {"n": len(live), "reason": ("full" if full else
                                            "forced" if forced else
                                            "deadline")}
        if obs_enabled():
            # one flush serves many requests: record EVERY trace id it
            # scores so tracemerge can attach the span to each trace
            tids = sorted({r.ctx[0] for r, _ in live if r.ctx is not None})
            if tids:
                attrs["traces"] = tids
        # waiters wake only after the span closes, so a request span that
        # awaits its score strictly encloses serve.flush in the timeline
        err: Optional[Exception] = None
        with obs_span("serve.flush", **attrs):
            try:
                scores = self._score([r for r, _ in live])
            except Exception as e:  # resolve waiters, never kill the worker
                err = e
        if err is not None:
            for _, f in live:
                f.set_exception(err)
            return
        for (_, f), s in zip(live, scores):
            f.set_result(float(s))
