"""ModelFleet: a keyed family of model handles on one serving substrate.

Photon ML reference counterpart: none — the reference trains and scores one
GAME model per driver run.  LinkedIn's production stack in front of it is
multi-tenant: per-vertical GLMix families, A/B variants, canary
generations, all resident at once.  This module is that layer for the
online engine built in PRs 4-15, under two resource rules the papers in
PAPERS.md argue for:

  **One AOT kernel cache** (Flare: the compiled-program family must stay
  fixed as tenancy grows).  Every per-model ``ScoringEngine`` is
  constructed on the fleet's shared ``KernelCache``; the cache key is
  ``(store.signature(), bucket)`` and ``signature()`` carries the model
  axis (``StoreConfig.fleet_axis``), so same-shape models SHARE executables
  outright — registering model N of an equal shape compiles nothing — and
  distinct-shape models coexist side by side without evicting each other
  (`KernelCache` pruning is liveness-based across all registered engines).

  **One device hot-row budget** (Snap ML: the fastest memory tier is a
  shared, explicitly-budgeted resource).  ``total_rows`` bounds the
  fleet-wide device-resident row count and per-tenant ``quotas`` carve it
  up; registration refuses a model that would push its tenant over quota
  (``TenantBudgetError``) and ``rebalance()`` re-verifies the invariant
  and exports per-tenant used/quota gauges every pass.

A handle is ``model_id -> (ScoringEngine, HotSwapper, tenant)``; the
swapper keeps per-model generation identity ``(generation,
delta_version)`` exactly as in single-model serving, so hot swap, deltas,
canary (policy.py) and shadow (shadow.py) all operate per model while the
executables and the row budget stay fleet-global.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional, Sequence, Tuple

from photon_ml_tpu.serving.batcher import BucketedBatcher
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                     CompactRandomCoordinate,
                                                     FixedCoordinate,
                                                     StoreConfig)
from photon_ml_tpu.serving.engine import KernelCache, ScoringEngine
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.swap import HotSwapper

DEFAULT_TENANT = "default"


class FleetError(ValueError):
    """Base for fleet registration/routing failures."""


class UnknownModelError(FleetError):
    """A request named a model_id no handle serves."""


class TenantBudgetError(FleetError):
    """Registering the model would push its tenant over its row quota."""


def store_device_rows(store: CoefficientStore) -> int:
    """Device-resident hot rows a store pins (per mesh shard): the sum of
    every non-fixed coordinate's device-table row count.  Fixed-effect
    weights are dense model state, not budgeted hot rows."""
    rows = 0
    for cid in store.order:
        c = store.coordinates[cid]
        if isinstance(c, FixedCoordinate):
            continue
        if isinstance(c, CompactRandomCoordinate):
            rows += int(c.hot.indices.shape[0])
        else:
            rows += int(c.table.shape[0])
    return rows


@dataclasses.dataclass
class ModelHandle:
    """One registered model: engine + swapper + tenant identity."""

    model_id: str
    tenant: str
    engine: ScoringEngine
    swapper: HotSwapper

    @property
    def store(self) -> CoefficientStore:
        return self.engine.store

    @property
    def identity(self) -> Tuple[int, int]:
        return self.swapper.identity

    @property
    def device_rows(self) -> int:
        return store_device_rows(self.engine.store)


class ModelFleet:
    """Keyed model handles sharing one kernel cache and one row budget.

    ``total_rows`` (None = unbudgeted) caps the fleet-wide device hot-row
    count; ``quotas`` maps tenant -> row quota (a tenant without an entry
    draws from the unreserved remainder of ``total_rows``).  All handles
    share ONE ``ServingMetrics`` so the snapshot stays the familiar
    single-engine aggregate; per-model/per-tenant detail rides the labeled
    ``fleet_*`` families (``ServingMetrics.fleet_view``).
    """

    def __init__(self, metrics: Optional[ServingMetrics] = None,
                 kernels: Optional[KernelCache] = None,
                 total_rows: Optional[int] = None,
                 quotas: Optional[Dict[str, int]] = None):
        self.metrics = metrics or ServingMetrics()
        self.kernels = kernels or KernelCache()
        self.total_rows = total_rows
        self.quotas: Dict[str, int] = dict(quotas or {})
        self._lock = threading.Lock()
        self._handles: Dict[str, ModelHandle] = {}
        self._default: Optional[str] = None
        self._batcher: Optional[BucketedBatcher] = None

    # -- registration ------------------------------------------------------
    @property
    def default_model(self) -> Optional[str]:
        # photonlint: disable=alias-escape -- Optional[str] snapshot;
        # strings cannot be mutated through the alias, and a stale
        # read races benignly with deregistration by design
        return self._default

    def models(self) -> Tuple[str, ...]:
        with self._lock:
            return tuple(self._handles)

    def __len__(self) -> int:
        with self._lock:
            return len(self._handles)

    def tenant_rows(self, tenant: str) -> int:
        """Device hot rows currently allocated to one tenant's models."""
        with self._lock:
            return sum(h.device_rows for h in self._handles.values()
                       if h.tenant == tenant)

    def quota_remaining(self, tenant: str) -> Optional[int]:
        """Rows the tenant may still allocate (None = unbudgeted).  A
        tenant without its own quota draws from what ``total_rows`` leaves
        after every reserved quota."""
        quota = self.quotas.get(tenant)
        if quota is None:
            if self.total_rows is None:
                return None
            reserved = sum(self.quotas.values())
            with self._lock:
                used = sum(h.device_rows for h in self._handles.values()
                           if h.tenant not in self.quotas)
            return max(self.total_rows - reserved - used, 0)
        return max(quota - self.tenant_rows(tenant), 0)

    def _check_budget(self, tenant: str, rows: int, model_id: str) -> None:
        remaining = self.quota_remaining(tenant)
        if remaining is not None and rows > remaining:
            raise TenantBudgetError(
                f"model {model_id!r} needs {rows} device rows but tenant "
                f"{tenant!r} has {remaining} left (quota "
                f"{self.quotas.get(tenant, self.total_rows)})")
        if self.total_rows is not None:
            with self._lock:
                used = sum(h.device_rows for h in self._handles.values())
            if used + rows > self.total_rows:
                raise TenantBudgetError(
                    f"model {model_id!r} needs {rows} device rows but the "
                    f"fleet has {self.total_rows - used} of {self.total_rows}"
                    " left")

    def adopt(self, model_id: str, engine: ScoringEngine,
              swapper: HotSwapper, tenant: str = DEFAULT_TENANT,
              default: bool = True) -> ModelHandle:
        """Bring an ALREADY-BUILT engine (cli/serve.py ``build_server``)
        into the fleet.  The first adopted engine's kernel cache becomes
        the fleet cache — its warmed executables are the family every
        later same-shape registration reuses; later adoptions must have
        been constructed on ``fleet.kernels``."""
        with self._lock:
            if not self._handles:
                self.kernels = engine.kernels
        if engine.kernels is not self.kernels:
            raise FleetError(
                f"model {model_id!r}: engine was built on a private kernel "
                "cache; construct it with kernels=fleet.kernels")
        self._check_budget(tenant, store_device_rows(engine.store), model_id)
        handle = ModelHandle(model_id=model_id, tenant=tenant,
                             engine=engine, swapper=swapper)
        with self._lock:
            if model_id in self._handles:
                raise FleetError(f"model {model_id!r} already registered")
            self._handles[model_id] = handle
            if default or self._default is None:
                self._default = model_id
            if self._batcher is None:
                # the fleet's bucket ladder: later registrations default to
                # the first engine's, so same-shape models plan identical
                # buckets and hit identical executables
                self._batcher = engine.batcher
        self._export_tenant_gauges()
        return handle

    def register_store(self, model_id: str, store: CoefficientStore,
                       tenant: str = DEFAULT_TENANT,
                       batcher: Optional[BucketedBatcher] = None,
                       warm: bool = True,
                       default: bool = False) -> ModelHandle:
        """Register an in-memory store as a new model: builds its engine on
        the SHARED kernel cache (+ shared metrics), warms the bucket ladder
        (free when an equal-signature model already warmed it), and wires a
        per-model HotSwapper."""
        self._check_budget(tenant, store_device_rows(store), model_id)
        engine = ScoringEngine(store, batcher=batcher or self._batcher,
                               metrics=self.metrics, kernels=self.kernels)
        if warm:
            engine.warm()
        swapper = HotSwapper(engine)
        return self.adopt(model_id, engine, swapper, tenant=tenant,
                          default=default)

    def register_dir(self, model_id: str, model_dir: str,
                     tenant: str = DEFAULT_TENANT,
                     config: Optional[StoreConfig] = None,
                     batcher: Optional[BucketedBatcher] = None,
                     version: str = "",
                     default: bool = False) -> ModelHandle:
        """Register a model directory (the cli ``--add-model`` path):
        load bundle -> store -> ``register_store``."""
        from photon_ml_tpu.storage.model_io import load_model_bundle
        bundle = load_model_bundle(model_dir)
        store = CoefficientStore.from_bundle(
            bundle, config=config or StoreConfig(),
            version=version or model_dir, metrics=self.metrics)
        handle = self.register_store(model_id, store, tenant=tenant,
                                     batcher=batcher, default=default)
        handle.swapper.set_base(model_dir)
        return handle

    def remove(self, model_id: str) -> None:
        """Evict a model: its engine stops pinning signatures in the shared
        cache and executables only it could reach are dropped."""
        with self._lock:
            handle = self._handles.pop(model_id, None)
            if handle is None:
                raise UnknownModelError(f"unknown model {model_id!r}")
            if self._default == model_id:
                self._default = next(iter(self._handles), None)
        self.kernels.drop_owner(handle.engine)
        self.kernels.prune()
        self._export_tenant_gauges()

    # -- routing -----------------------------------------------------------
    def resolve(self, model_id: Optional[str]) -> ModelHandle:
        """Request routing: ``None`` (the pre-fleet wire form) routes to
        the default model; an unknown id raises ``UnknownModelError``."""
        with self._lock:
            mid = model_id if model_id is not None else self._default
            handle = self._handles.get(mid) if mid is not None else None
        if handle is None:
            raise UnknownModelError(f"unknown model {model_id!r}")
        return handle

    def handle(self, model_id: str) -> ModelHandle:
        with self._lock:
            h = self._handles.get(model_id)
        if h is None:
            raise UnknownModelError(f"unknown model {model_id!r}")
        return h

    # -- maintenance -------------------------------------------------------
    def _export_tenant_gauges(self) -> None:
        with self._lock:
            handles = list(self._handles.values())
        used: Dict[str, int] = {}
        for h in handles:
            used[h.tenant] = used.get(h.tenant, 0) + h.device_rows
        for tenant, rows in used.items():
            quota = self.quotas.get(tenant, self.total_rows or 0)
            self.metrics.set_tenant_rows(tenant, rows, quota)

    def rebalance(self) -> Dict[str, Dict[str, Tuple[int, int]]]:
        """One hot-set pass over EVERY model (model_id -> its per-cid
        (promotions, demotions)), then re-verify the tenant row invariant
        and export per-tenant used/quota gauges.  Rebalance moves rows
        within each store's fixed device tables, so a quota violation here
        means registration-time accounting was bypassed — fail loudly."""
        with self._lock:
            handles = dict(self._handles)
        moves = {mid: h.store.rebalance() for mid, h in handles.items()}
        for tenant in {h.tenant for h in handles.values()}:
            quota = self.quotas.get(tenant)
            if quota is not None and self.tenant_rows(tenant) > quota:
                raise TenantBudgetError(
                    f"tenant {tenant!r} holds {self.tenant_rows(tenant)} "
                    f"device rows over quota {quota}")
        self._export_tenant_gauges()
        return moves

    def status(self) -> dict:
        """Introspection for the ``fleet`` command / tests."""
        with self._lock:
            handles = dict(self._handles)
            default = self._default
        return {
            "default": default,
            "models": {
                mid: {
                    "tenant": h.tenant,
                    "generation": h.store.generation,
                    "delta_version": h.swapper.delta_version,
                    "version": h.store.version,
                    "device_rows": h.device_rows,
                    "compiles": h.engine.compile_count,
                }
                for mid, h in handles.items()
            },
            "kernels": {
                "executables": len(self.kernels),
                "signatures": len(self.kernels.signatures()),
                "compiles": self.kernels.compile_count,
            },
            "budget": {
                "total_rows": self.total_rows,
                "quotas": dict(self.quotas),
                "used": {t: self.tenant_rows(t)
                         for t in {h.tenant for h in handles.values()}},
            },
        }
