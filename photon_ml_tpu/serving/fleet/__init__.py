"""photonfleet: multi-model serving on one engine substrate.

One ``ModelFleet`` (registry.py) keys a family of model handles —
model_id -> (CoefficientStore, HotSwapper, tenant) — that share ONE AOT
``KernelCache`` (same-shape models share executables; distinct shapes
coexist) and ONE device hot-row budget with per-tenant quotas.  On top of
the handles: ``CanaryPolicy``/``CanaryController`` (policy.py) run
deterministic-split canary rollouts with auto-promote/auto-rollback, and
``ShadowScorer`` (shadow.py) scores a candidate against live traffic
while serving the active generation.  Tenancy reaches the wire through
the frontend (``Request.model``, per-tenant tokens and admission budgets)
and the metrics through the labeled ``fleet_*`` families
(``ServingMetrics.fleet_view``).
"""

from photon_ml_tpu.serving.fleet.policy import (CANARY,  # noqa: F401
                                                IDLE, PROMOTED, ROLLED_BACK,
                                                CanaryController,
                                                CanaryPolicy, request_key,
                                                split_preview, stable_bucket)
from photon_ml_tpu.serving.fleet.registry import (DEFAULT_TENANT,  # noqa: F401
                                                  FleetError, ModelFleet,
                                                  ModelHandle,
                                                  TenantBudgetError,
                                                  UnknownModelError,
                                                  store_device_rows)
from photon_ml_tpu.serving.fleet.router import FleetRouter  # noqa: F401
from photon_ml_tpu.serving.fleet.shadow import (ShadowScorer,  # noqa: F401
                                                shadow_overhead_ratio)
