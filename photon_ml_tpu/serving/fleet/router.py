"""FleetRouter: the score-path indirection canary and shadow hook into.

Both serving edges (the stdio loop in ``cli/serve.py`` and the asyncio
frontend) score through per-model ``AsyncBatcher``s whose score function
is ``router.score(model_id, requests)`` — one seam where a running canary
episode (policy.py) or an attached shadow scorer (shadow.py) interposes
on ONE model's traffic while every other model scores straight through
its engine.  The router owns the per-model canary/shadow registries so
control commands (``{"cmd": "canary"}`` / ``promote`` / ``rollback`` /
``shadow``) and the score path agree on what is active.

Threading: ``score`` runs on the model's batcher worker; control methods
run on the edge's command path AFTER a drain barrier (the same quiesce
rule as hot swap), so an episode never starts or force-settles with that
model's requests in flight — which is also what makes "zero admitted
request loss across rollback" a structural property rather than a race.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from photon_ml_tpu.chaos.health import HealthState
from photon_ml_tpu.serving.batcher import Request
from photon_ml_tpu.serving.coefficient_store import CoefficientStore
from photon_ml_tpu.serving.fleet.policy import (CANARY, CanaryController,
                                                CanaryPolicy)
from photon_ml_tpu.serving.fleet.registry import ModelFleet
from photon_ml_tpu.serving.fleet.shadow import ShadowScorer


class FleetRouter:
    """Canary/shadow-aware per-model scoring over a ModelFleet."""

    def __init__(self, fleet: ModelFleet,
                 health: Optional[HealthState] = None):
        self.fleet = fleet
        self.health = health
        self.canaries: Dict[str, CanaryController] = {}
        self.shadows: Dict[str, ShadowScorer] = {}

    # -- the scoring seam --------------------------------------------------
    def score(self, model_id: str, requests: Sequence[Request],
              predict_mean: bool = False) -> np.ndarray:
        """Score one model's batch through whatever policy is active on
        it: a RUNNING canary episode splits the batch, an attached shadow
        dual-scores it, plain models go straight to the engine."""
        handle = self.fleet.handle(model_id)
        ctl = self.canaries.get(model_id)
        if ctl is not None and ctl.state == CANARY:
            return ctl.score(requests, predict_mean=predict_mean)
        shadow = self.shadows.get(model_id)
        if shadow is not None:
            return shadow.score(requests, predict_mean=predict_mean)
        return handle.engine.score_requests(requests,
                                            predict_mean=predict_mean)

    # -- canary control ----------------------------------------------------
    def start_canary(self, model_id: str, candidate: CoefficientStore,
                     policy: Optional[CanaryPolicy] = None,
                     model_dir: Optional[str] = None) -> CanaryController:
        ctl = CanaryController(self.fleet.handle(model_id), policy,
                               health=self.health)
        ctl.start(candidate, model_dir=model_dir)
        self.canaries[model_id] = ctl
        return ctl

    def canary(self, model_id: str) -> Optional[CanaryController]:
        return self.canaries.get(model_id)

    def promote(self, model_id: str) -> CanaryController:
        """Operator-forced promote (still via the swap lock + chaos
        seam; an injected fault still becomes a rollback)."""
        ctl = self._require_canary(model_id)
        if ctl.state == CANARY:
            ctl.promote()
        return ctl

    def rollback(self, model_id: str,
                 reason: str = "operator") -> CanaryController:
        ctl = self._require_canary(model_id)
        if ctl.state == CANARY:
            ctl.rollback(reason)
        return ctl

    def _require_canary(self, model_id: str) -> CanaryController:
        ctl = self.canaries.get(model_id)
        if ctl is None:
            raise ValueError(f"no canary episode on model {model_id!r}")
        return ctl

    # -- shadow control ----------------------------------------------------
    def attach_shadow(self, model_id: str,
                      shadow: CoefficientStore) -> ShadowScorer:
        scorer = ShadowScorer(self.fleet.handle(model_id), shadow)
        self.shadows[model_id] = scorer
        return scorer

    def detach_shadow(self, model_id: str) -> bool:
        return self.shadows.pop(model_id, None) is not None

    # -- introspection -----------------------------------------------------
    def status(self) -> dict:
        """Fleet status + per-model policy state (the ``fleet`` cmd)."""
        out = self.fleet.status()
        out["canary"] = {mid: ctl.status()
                        for mid, ctl in self.canaries.items()}
        out["shadow"] = {mid: sh.drift_view()
                        for mid, sh in self.shadows.items()}
        return out
