"""Shadow scoring: score both generations, serve the old, measure drift.

Photon ML reference counterpart: none — offline validation in the
reference world is a batch AUC job over a holdout set.  Shadow mode is
the online complement: the CANDIDATE generation scores the live request
stream at full fidelity (real features, real entity mix, real buckets)
while the ACTIVE generation's scores are the ones served, so a bad
candidate can be observed for as long as needed at zero user risk — the
read-only half of the canary policy.

Per-request ``|shadow - primary|`` drift is recorded into the labeled
histogram family ``fleet_shadow_drift{model=, bucket=}`` — bucketed by
the micro-batch bucket the pair scored under, because drift that only
appears at one padded shape is a kernel problem, not a model problem —
plus a ``fleet_shadow_pairs_total{model=}`` pair count
(``ServingMetrics.fleet_view()["shadow"]``).

Both legs run under ONE photonpulse trace: ``score`` wraps them in
``fleet.serve`` / ``fleet.shadow`` spans stamped with the requests' trace
ids, and the engine's ``serve.execute`` spans inherit the same ids from
the requests themselves — so a ``tools/tracemerge.py`` timeline shows the
primary and shadow executions of one request joined under one trace id.

Executables come from the shared ``KernelCache``: a same-shape shadow
store warms for free, and the whole shadow episode performs zero
compiles — the overhead is exactly one extra execution per batch, which
``bench.py --fleet`` reports as the shadow overhead ratio.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.obs.trace import enabled as obs_enabled
from photon_ml_tpu.obs.trace import span as obs_span
from photon_ml_tpu.serving.batcher import Request
from photon_ml_tpu.serving.coefficient_store import CoefficientStore
from photon_ml_tpu.serving.fleet.registry import ModelHandle


class ShadowScorer:
    """Dual-leg scorer for one model handle (module docstring)."""

    def __init__(self, handle: ModelHandle, shadow: CoefficientStore,
                 warm: bool = True):
        self.handle = handle
        self.shadow = shadow
        if warm:
            # free when the shadow store's signature matches a live one
            handle.engine.warm(store=shadow)

    def _trace_attrs(self, requests: Sequence[Request]) -> dict:
        if not obs_enabled():
            return {}
        tids = sorted({r.ctx[0] for r in requests if r.ctx is not None})
        return {"traces": tids} if tids else {}

    def score(self, requests: Sequence[Request],
              predict_mean: bool = False) -> np.ndarray:
        """Score both legs; SERVE the primary (active generation).  The
        shadow leg's scores never leave this method — they exist only to
        be differenced."""
        engine = self.handle.engine
        n = len(requests)
        if n == 0:
            return engine.score_requests(requests,
                                         predict_mean=predict_mean)
        attrs = self._trace_attrs(requests)
        with obs_span("fleet.serve", model=self.handle.model_id,
                      rows=n, **attrs):
            primary = engine.score_requests(requests,
                                            predict_mean=predict_mean)
        with obs_span("fleet.shadow", model=self.handle.model_id,
                      rows=n, **attrs):
            shadowed = engine.score_requests(requests,
                                             predict_mean=predict_mean,
                                             store=self.shadow)
        self._record_drift(requests, primary, shadowed)
        return primary

    def _record_drift(self, requests: Sequence[Request],
                      primary: np.ndarray, shadowed: np.ndarray) -> None:
        """Attribute each pair's drift to the micro-batch bucket it scored
        under — the SAME plan both legs used (one batcher, one n)."""
        metrics = self.handle.engine.metrics
        drift = np.abs(np.asarray(shadowed) - np.asarray(primary))
        for mb in self.handle.engine.batcher.plan(len(requests)):
            for i in range(mb.start, mb.stop):
                metrics.observe_shadow_drift(self.handle.model_id,
                                             mb.bucket, float(drift[i]))

    def drift_view(self) -> dict:
        """This model's slice of ``ServingMetrics.fleet_view()['shadow']``
        (``{"pairs": n, "drift": {bucket: histogram-snapshot}}``)."""
        view = self.handle.engine.metrics.fleet_view()["shadow"]
        return view.get(self.handle.model_id, {"pairs": 0, "drift": {}})


def shadow_overhead_ratio(dual_s: float, single_s: float) -> float:
    """Bench helper: wall-time ratio of dual-leg to single-leg scoring
    (ideal ~2.0 for same-shape legs; >> 2 would mean the shadow leg is
    compiling, which the shared kernel cache forbids)."""
    return dual_s / single_s if single_s > 0 else 0.0
