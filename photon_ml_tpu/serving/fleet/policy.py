"""Canary rollout: safe-deployment POLICY on top of the hot-swap mechanism.

Photon ML reference counterpart: none — model rotation in the reference's
world is an offline artifact push; whether the new artifact is SAFE is
left to the serving infrastructure.  This module is that judgment, made
deterministic and automatic:

  **Deterministic traffic split.**  ``stable_bucket`` hashes the request
  key (``Request.uid``, falling back to the entity-id map) with BLAKE2b —
  not an RNG — so the canary slice is a pure function of the request
  stream: a replayed log splits identically, a test predicts exactly which
  uids ride the candidate, and two frontends splitting the same stream
  agree without coordination.

  **Both legs scored, drift observed.**  A canary-leg request is scored on
  the CANDIDATE (that score is served) and on the ACTIVE generation (that
  score is the reference); ``|new - old|`` feeds the drift gate.  Control
  traffic never touches the candidate.  Executables come from the shared
  ``KernelCache`` — the candidate was warmed at ``start``, so the whole
  episode performs zero compiles.

  **Auto-promote / auto-rollback.**  After every scored batch the
  controller settles: a clean observation window (``min_observations``
  canary scores with mean drift <= ``max_drift`` and the PR-14 health
  plane ready) promotes — the pointer flip runs through
  ``HotSwapper.activate_store``, i.e. under the swap lock and through the
  SAME ``swap.activate`` chaos seam as a deployment swap.  A drift breach
  or a not-ready health plane rolls back.  Either way the losing store is
  simply dropped: the active generation object was never touched, so
  rollback leaves it serving bitwise-identically, and every admitted
  request was scored by SOME generation — zero loss by construction.
  An injected fault at promotion becomes a rollback (``InjectedCrash``
  propagates — a crash is never handled, exactly like swap).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.chaos.health import HealthState
from photon_ml_tpu.chaos.injector import InjectedCrash, InjectedFault
from photon_ml_tpu.obs.trace import span as obs_span
from photon_ml_tpu.serving.batcher import Request
from photon_ml_tpu.serving.coefficient_store import CoefficientStore
from photon_ml_tpu.serving.fleet.registry import ModelHandle

# canary episode states
IDLE = "idle"
CANARY = "canary"
PROMOTED = "promoted"
ROLLED_BACK = "rolled_back"

_BUCKETS = 10_000  # split granularity: 0.01% steps


def stable_bucket(key: str, buckets: int = _BUCKETS) -> int:
    """Request key -> bucket in ``[0, buckets)`` via BLAKE2b — stable
    across processes, Python hash seeds, and replays (``hash()`` is none
    of those).  The canary slice is ``bucket < fraction * buckets``."""
    h = hashlib.blake2b(str(key).encode("utf-8"), digest_size=8)
    return int.from_bytes(h.digest(), "big") % buckets


def request_key(req: Request) -> str:
    """The deterministic key a request is split on: its uid when the
    client set one, else its entity-id map (the same entities always land
    on the same leg, which is what an A/B read needs)."""
    if req.uid is not None:
        return str(req.uid)
    return json.dumps(req.ids, sort_keys=True)


@dataclasses.dataclass(frozen=True)
class CanaryPolicy:
    """Knobs for one rollout episode.

    ``fraction``: slice of traffic (by stable key hash) riding the
    candidate.  ``min_observations``: canary scores needed for a clean
    window.  ``max_drift``: mean ``|candidate - active|`` score drift the
    window may carry and still promote; above it the episode rolls back.
    ``health_poll_s``: how often the health plane is re-polled (readyz
    walks every check; the throttle keeps it off the per-batch path).
    """

    fraction: float = 0.25
    min_observations: int = 100
    max_drift: float = 1e-6
    health_poll_s: float = 0.25

    def __post_init__(self):
        if not 0.0 < self.fraction <= 1.0:
            raise ValueError(f"fraction must be in (0, 1], got "
                             f"{self.fraction}")
        if self.min_observations < 1:
            raise ValueError("min_observations must be >= 1, got "
                             f"{self.min_observations}")
        if self.max_drift < 0:
            raise ValueError(f"max_drift must be >= 0, got {self.max_drift}")


class CanaryController:
    """One model handle's rollout state machine (module docstring).

    Single-owner state: score/settle run on the handle's dispatch path
    (one thread), like the frontend's admission latch — documented rather
    than defended.  The pointer flip itself goes through the swapper's
    lock.
    """

    def __init__(self, handle: ModelHandle,
                 policy: Optional[CanaryPolicy] = None,
                 health: Optional[HealthState] = None,
                 clock=time.monotonic):
        self.handle = handle
        self.policy = policy or CanaryPolicy()
        self.health = health
        self._clock = clock
        self.state = IDLE
        self.candidate: Optional[CoefficientStore] = None
        self.candidate_dir: Optional[str] = None
        self.observations = 0
        self.drift_sum = 0.0
        self.drift_max = 0.0
        self.started_at: Optional[float] = None
        self.settled_at: Optional[float] = None
        self.rollback_reason: Optional[str] = None
        self._health_checked_at: Optional[float] = None
        self._health_ok = True
        self._registry = handle.engine.metrics.registry

    # -- episode lifecycle -------------------------------------------------
    def start(self, candidate: CoefficientStore,
              model_dir: Optional[str] = None) -> None:
        """Begin an episode: warm the candidate on the shared cache (free
        for a same-shape generation) and start splitting traffic."""
        if self.state == CANARY:
            raise RuntimeError("canary episode already running")
        self.handle.engine.warm(store=candidate)
        self.candidate = candidate
        self.candidate_dir = model_dir
        self.state = CANARY
        self.observations = 0
        self.drift_sum = 0.0
        self.drift_max = 0.0
        self.started_at = self._clock()
        self.settled_at = None
        self.rollback_reason = None
        self._health_checked_at = None
        self._transition_metric(CANARY)

    def _transition_metric(self, state: str) -> None:
        self._registry.inc("fleet_canary_transitions_total",
                           model=self.handle.model_id, state=state)

    def is_canary(self, req: Request) -> bool:
        """Deterministic membership of the canary slice."""
        return (stable_bucket(request_key(req))
                < self.policy.fraction * _BUCKETS)

    @property
    def mean_drift(self) -> float:
        return self.drift_sum / self.observations if self.observations \
            else 0.0

    @property
    def settle_s(self) -> Optional[float]:
        """Episode wall time, start -> promote/rollback (bench metric)."""
        if self.started_at is None or self.settled_at is None:
            return None
        return self.settled_at - self.started_at

    # -- scoring -----------------------------------------------------------
    def score(self, requests: Sequence[Request],
              predict_mean: bool = False) -> np.ndarray:
        """Score a batch under the split: control rows on the active
        generation, canary rows on BOTH (candidate served, active as the
        drift reference), then settle.  With no episode running this is
        exactly ``engine.score_requests``."""
        engine = self.handle.engine
        if self.state != CANARY or not requests:
            return engine.score_requests(requests,
                                         predict_mean=predict_mean)
        canary_ix = [i for i, r in enumerate(requests) if self.is_canary(r)]
        control_ix = [i for i in range(len(requests))
                      if i not in set(canary_ix)]
        out: Optional[np.ndarray] = None
        if control_ix:
            control = engine.score_requests(
                [requests[i] for i in control_ix],
                predict_mean=predict_mean)
            out = np.empty(len(requests), control.dtype)
            out[control_ix] = control
        if canary_ix:
            leg = [requests[i] for i in canary_ix]
            with obs_span("fleet.canary", model=self.handle.model_id,
                          rows=len(leg)):
                new = engine.score_requests(leg, predict_mean=predict_mean,
                                            store=self.candidate)
                old = engine.score_requests(leg, predict_mean=predict_mean)
            drift = np.abs(np.asarray(new) - np.asarray(old))
            self.observations += len(leg)
            self.drift_sum += float(drift.sum())
            self.drift_max = max(self.drift_max, float(drift.max()))
            if out is None:
                out = np.empty(len(requests), new.dtype)
            out[canary_ix] = new
        self.maybe_settle()
        return out

    # -- settling ----------------------------------------------------------
    def _healthy(self) -> bool:
        if self.health is None:
            return True
        now = self._clock()
        if (self._health_checked_at is None
                or now - self._health_checked_at >= self.policy.health_poll_s):
            self._health_ok = bool(self.health.readyz()[0])
            self._health_checked_at = now
        return self._health_ok

    def maybe_settle(self) -> str:
        """One settle decision; returns the (possibly new) state.  Health
        is checked FIRST so a degraded plane rolls back even before the
        window fills — the rollback edge chaos tests lean on this."""
        if self.state != CANARY:
            return self.state
        if not self._healthy():
            self.rollback("health_not_ready")
        elif self.observations >= self.policy.min_observations:
            if self.mean_drift > self.policy.max_drift:
                self.rollback("score_drift")
            else:
                self.promote()
        return self.state

    def promote(self) -> None:
        """Flip the handle to the candidate through the swapper (swap
        lock + ``swap.activate`` chaos seam).  An injected FAULT becomes a
        rollback — the old generation never stopped serving; an injected
        CRASH propagates, as everywhere."""
        assert self.candidate is not None
        try:
            self.handle.swapper.activate_store(self.candidate,
                                              model_dir=self.candidate_dir)
        except InjectedCrash:
            raise
        except InjectedFault:
            self.rollback("promotion_fault")
            return
        self.state = PROMOTED
        self.settled_at = self._clock()
        self.candidate = None
        self._transition_metric(PROMOTED)

    def rollback(self, reason: str) -> None:
        """Drop the candidate; the active generation (never touched) keeps
        serving.  Recorded under ``fleet_canary_transitions_total`` with
        the triggering gate as a label."""
        self.state = ROLLED_BACK
        self.settled_at = self._clock()
        self.rollback_reason = reason
        self.candidate = None
        self._registry.inc("fleet_canary_rollbacks_total",
                           model=self.handle.model_id, reason=reason)
        self._transition_metric(ROLLED_BACK)

    def status(self) -> dict:
        return {
            "state": self.state,
            "fraction": self.policy.fraction,
            "observations": self.observations,
            "mean_drift": self.mean_drift,
            "max_drift": self.drift_max,
            "rollback_reason": self.rollback_reason,
            "settle_s": self.settle_s,
        }


def split_preview(uids: Sequence[object],
                  fraction: float) -> Tuple[List[object], List[object]]:
    """Which of ``uids`` would ride the canary at ``fraction`` — the
    deterministic-split oracle tests and operators use."""
    canary, control = [], []
    for uid in uids:
        (canary if stable_bucket(str(uid)) < fraction * _BUCKETS
         else control).append(uid)
    return canary, control
