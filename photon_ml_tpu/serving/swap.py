"""Atomic hot model reload.

Photon ML reference counterpart: LinkedIn's GLMix serving rotates newly
trained model artifacts into the online stores (new PalDB store files built
offline, then the serving layer cuts over) — the batch repo itself has no
in-process swap, so this module is the piece the paper describes but the
reference leaves to infrastructure.

Protocol (all failure paths leave the OLD version serving):

  1. load the new model directory through
     ``storage/model_io.load_model_bundle`` — every structural problem
     (missing metadata.json / ``*.idx`` / ``*.entities.json``, corrupt
     files) surfaces as the typed ``ModelLoadError``, never a raw
     ``KeyError``;
  2. build a fresh ``CoefficientStore`` under the SAME StoreConfig policy
     as the active generation;
  3. **warm** the new store: compile executables for the bucket ladder so
     no post-swap request pays a compile (same-shape versions reuse the old
     executables outright — the signature cache key makes that free);
  4. flip the engine's generation pointer atomically
     (``ScoringEngine.activate``).  In-flight requests snapshotted the old
     store and finish on it.

``swap`` is synchronous; ``swap_async`` runs the same protocol on a
background thread (the load/warm work happens off the request path either
way — only the pointer flip touches the engine).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence

from photon_ml_tpu.serving.coefficient_store import CoefficientStore
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.storage.model_io import ModelLoadError, load_model_bundle
from photon_ml_tpu.utils.logging import Timed

logger = logging.getLogger("photon_ml_tpu.serving.swap")


class HotSwapper:
    """Load-warm-flip model rotation for one ScoringEngine."""

    def __init__(self, engine: ScoringEngine,
                 warm_buckets: Optional[Sequence[int]] = None):
        self.engine = engine
        self.warm_buckets = warm_buckets  # None -> the batcher's ladder
        self._swap_lock = threading.Lock()  # one swap in flight at a time

    def swap(self, model_dir: str, version: str = "") -> bool:
        """Returns True when the new version is serving; False when the new
        directory was rejected (the old version keeps serving untouched)."""
        metrics = self.engine.metrics
        with self._swap_lock:
            old = self.engine.store
            try:
                with Timed(f"serving.swap.load {model_dir}", logger,
                           sink=metrics.phase):
                    bundle = load_model_bundle(model_dir)
                    new = CoefficientStore.from_bundle(
                        bundle, config=old.config,
                        version=version or model_dir, metrics=metrics)
                self.engine.warm(self.warm_buckets, store=new)
            except (ModelLoadError, ValueError) as e:
                metrics.inc("swap_failures")
                logger.error("hot swap rejected %s (still serving gen %d, "
                             "version %r): %s", model_dir, old.generation,
                             old.version, e)
                return False
            self.engine.activate(new)
            metrics.inc("swaps")
            logger.info("hot swap: gen %d (version %r) -> gen %d (version "
                        "%r)", old.generation, old.version, new.generation,
                        new.version)
            return True

    def swap_async(self, model_dir: str, version: str = "") -> threading.Thread:
        """Run ``swap`` on a daemon thread; returns the thread (join it to
        observe completion).  Requests keep flowing on the old generation
        until the flip."""
        t = threading.Thread(target=self.swap, args=(model_dir, version),
                             daemon=True, name="photon-serving-swap")
        t.start()
        return t
