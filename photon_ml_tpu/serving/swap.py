"""Atomic hot model reload.

Photon ML reference counterpart: LinkedIn's GLMix serving rotates newly
trained model artifacts into the online stores (new PalDB store files built
offline, then the serving layer cuts over) — the batch repo itself has no
in-process swap, so this module is the piece the paper describes but the
reference leaves to infrastructure.

Protocol (all failure paths leave the OLD version serving):

  1. load the new model directory through
     ``storage/model_io.load_model_bundle`` — every structural problem
     (missing metadata.json / ``*.idx`` / ``*.entities.json``, corrupt
     files) surfaces as the typed ``ModelLoadError``, never a raw
     ``KeyError``;
  2. build a fresh ``CoefficientStore`` under the SAME StoreConfig policy
     as the active generation;
  3. **warm** the new store: compile executables for the bucket ladder so
     no post-swap request pays a compile (same-shape versions reuse the old
     executables outright — the signature cache key makes that free);
  4. flip the engine's generation pointer atomically
     (``ScoringEngine.activate``).  In-flight requests snapshotted the old
     store and finish on it.

``swap`` is synchronous; ``swap_async`` runs the same protocol on a
background thread (the load/warm work happens off the request path either
way — only the pointer flip touches the engine).

Between full swaps, **streaming deltas** (``apply_delta`` /
``publish_delta``) scatter single online-learned coefficient rows into the
live store (``CoefficientStore.apply_delta``: archive write + device
scatter + LRU invalidation) without a generation flip.  The swapper is
where they enter so the coefficient state has ONE version identity:
``(generation, delta_version)`` — ``delta_version`` counts deltas applied
to the current generation and resets to 0 at every successful swap.

With a ``delta_log`` attached (online/delta_log.py) the swapper becomes
the online-learning hub: every applied delta is also appended to the
durable log under its identity (apply-then-log — the in-memory store is
volatile, the log is the durable authority; a crash between the two loses
an update the same way it would mid-apply, never re-orders), and
``swap()`` REPLAYS the log into the incoming store after warm and before
``activate``, so the generation flip never loses rows the trainer
published while the new snapshot was training or loading.  When the
swapper OWNS the log (``log_owner=True`` — the trainer/writer process) it
also compacts segments older than the new generation after the flip.  A
follower process (``cli/serve.py --delta-log``) attaches the same log
with ``log_owner=False``: replay-before-activate still runs, but it never
appends (its process-local generation numbers would corrupt the writer's
identity order) and never compacts (the segments belong to the writer).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.chaos.injector import fault as _chaos_fault
from photon_ml_tpu.obs.pulse.context import current as ctx_current
from photon_ml_tpu.obs.pulse.context import note_delta as ctx_note_delta
from photon_ml_tpu.obs.trace import enabled as obs_enabled
from photon_ml_tpu.obs.trace import span as obs_span
from photon_ml_tpu.online.catchup import replay_into_store
from photon_ml_tpu.online.delta_log import DeltaLog, DeltaRecord
from photon_ml_tpu.serving.coefficient_store import CoefficientStore
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.storage.model_io import ModelLoadError, load_model_bundle
from photon_ml_tpu.utils.logging import Timed

logger = logging.getLogger("photon_ml_tpu.serving.swap")


class HotSwapper:
    """Load-warm-flip model rotation for one ScoringEngine."""

    def __init__(self, engine: ScoringEngine,
                 warm_buckets: Optional[Sequence[int]] = None,
                 delta_log: Optional[DeltaLog] = None,
                 log_owner: bool = True):
        self.engine = engine
        self.warm_buckets = warm_buckets  # None -> the batcher's ladder
        self.delta_log = delta_log
        self.log_owner = log_owner
        # one swap OR delta in flight at a time — deltas must not land on a
        # store that is mid-flip, and delta_version must pair with exactly
        # one generation
        self._swap_lock = threading.Lock()
        self.delta_version = 0  # deltas applied to the CURRENT generation
        # (model_dir, replay_floor) of the serving base, written as ONE
        # tuple so cross-thread readers (a photonrepl snapshot source)
        # never see a torn pair.  The floor is the LOG generation the base
        # was activated at: replay skips records below it.  Compaction
        # normally drops those, but a photonrepl retention pin can keep
        # pre-swap segments alive for a lagging subscriber — and those
        # records belong to a SUPERSEDED base, never to this one.
        self._base: Tuple[Optional[str], int] = (None, 0)
        # photonrepl hook, called as on_swap(model_dir, generation) after a
        # successful activate+compact — lets a replication server raise its
        # base floor and ship the new snapshot to live subscribers
        self.on_swap: Optional[Callable[[str, int], None]] = None
        # When True (set by online.replication.attach_replication), an
        # owner's swap treats the incoming base as AUTHORITATIVE: pre-swap
        # log records are not replayed onto it.  A replicated owner's live
        # state must stay derivable as ``snapshot dir + retained records at
        # or above the floor`` — replaying records that compaction then
        # drops would leave the owner serving rows no subscriber can ever
        # bootstrap.  Without replication the default (replay everything
        # retained) keeps standalone owners from stepping back past online
        # updates on a same-dir reload.
        self.base_supersedes_log = False

    @property
    def identity(self) -> Tuple[int, int]:
        """The live coefficient state's ``(generation, delta_version)``."""
        return (self.engine.store.generation, self.delta_version)

    @property
    def model_dir(self) -> Optional[str]:
        """Directory of the serving base (None until set_base / a swap)."""
        return self._base[0]

    @property
    def replay_floor(self) -> int:
        """Log generation the serving base was activated at."""
        return self._base[1]

    def set_base(self, model_dir: Optional[str], replay_floor: int = 0,
                 ) -> None:
        """Record the serving base pair (atomic for cross-thread
        readers).  ``cli/serve.py build_server`` calls this with the dir
        it loaded; a photonrepl replica passes the owner's floor too."""
        with self._swap_lock:
            self._base = (model_dir, int(replay_floor))

    def serving_base(self) -> Tuple[Optional[str], int]:
        """The ``(model_dir, replay_floor)`` pair, read atomically — the
        photonrepl owner's snapshot source."""
        # photonlint: disable=alias-escape -- the base pair is an
        # immutable tuple REBOUND under _swap_lock (never mutated);
        # returning it is exactly the atomic-pair-read this class
        # exists to provide
        return self._base

    def swap(self, model_dir: str, version: str = "",
             replay_floor: Optional[int] = None) -> bool:
        """Returns True when the new version is serving; False when the new
        directory was rejected (the old version keeps serving untouched).

        ``replay_floor`` is the LOG generation the incoming base was built
        at: replay-before-activate skips records below it.  A photonrepl
        replica passes the generation shipped with the snapshot (its
        process-local store generations mean nothing to the owner's log);
        an owning swapper leaves it None and uses the activated store's own
        generation, which IS the log generation it mints."""
        metrics = self.engine.metrics
        with obs_span("serve.swap", model_dir=model_dir), self._swap_lock:
            old = self.engine.store
            try:
                with Timed(f"serving.swap.load {model_dir}", logger,
                           sink=metrics.phase):
                    bundle = load_model_bundle(model_dir)
                    new = CoefficientStore.from_bundle(
                        bundle, config=old.config,
                        version=version or model_dir, metrics=metrics)
                self.engine.warm(self.warm_buckets, store=new)
            except (ModelLoadError, ValueError) as e:
                metrics.inc("swap_failures")
                logger.error("hot swap rejected %s (still serving gen %d, "
                             "version %r): %s", model_dir, old.generation,
                             old.version, e)
                return False
            if self.delta_log is not None:
                # replay-before-activate: rows the trainer published since
                # the incoming snapshot was cut replay onto the new store
                # BEFORE the flip — the generation change never steps back
                # past an online update.  Ordered replay of everything at
                # or above the current base's floor: full-row records make
                # it an idempotent overwrite.  Records BELOW the floor are
                # skipped — compaction usually dropped them already, but a
                # photonrepl retention pin can keep those segments alive
                # for a lagging subscriber, and they describe a base this
                # store superseded.
                if replay_floor is not None:
                    floor = replay_floor
                elif self.log_owner and self.base_supersedes_log:
                    # replicated owner: the new base supersedes the whole
                    # retained log (see __init__) — its freshly minted
                    # generation is above every logged record
                    floor = new.generation
                else:
                    floor = self._base[1]
                stats = replay_into_store(
                    new,
                    (r for r in self.delta_log.replay()
                     if r.generation >= floor),
                    registry=metrics.registry)
                metrics.inc("swap_replayed_deltas", stats.applied)
                if stats.applied or stats.rejected:
                    logger.info(
                        "hot swap: replayed %d delta(s) onto incoming gen "
                        "%d (%d rejected)", stats.applied, new.generation,
                        stats.rejected)
            act = _chaos_fault("swap.activate")
            if act is not None:
                # chaos: crash between the model-dir write/replay and the
                # pointer flip — the window every swap protocol has to
                # survive.  InjectedCrash propagates (a crash is not
                # handled); the old generation keeps serving, exactly as
                # a real process death would leave a restarted sibling.
                raise act.to_error()
            self.engine.activate(new)
            self.delta_version = 0  # fresh generation: no deltas yet
            if replay_floor is not None:
                new_floor = replay_floor
            elif self.log_owner:
                # owner: the activated store's generation is the log's
                new_floor = new.generation
            else:
                # follower without an explicit floor keeps its old floor —
                # its process-local generations mean nothing to the log
                new_floor = self._base[1]
            self._base = (model_dir, new_floor)
            if self.delta_log is not None and self.log_owner:
                self.delta_log.compact(new.generation)
            metrics.inc("swaps")
            logger.info("hot swap: gen %d (version %r) -> gen %d (version "
                        "%r)", old.generation, old.version, new.generation,
                        new.version)
            if self.on_swap is not None:
                try:
                    self.on_swap(model_dir, new.generation)
                except Exception:
                    logger.exception("hot swap: on_swap hook failed")
            return True

    def activate_store(self, store: CoefficientStore,
                       model_dir: Optional[str] = None,
                       chaos_point: str = "swap.activate") -> None:
        """Flip the engine to an ALREADY-warmed in-memory store — the
        canary promote path (serving/fleet/policy.py): the candidate store
        has been serving its traffic slice for the whole observation
        window, so load/warm/replay have long since happened; promotion is
        only the pointer flip, run under the same swap lock and through
        the same ``swap.activate`` chaos seam as a full swap, so fault
        schedules written against swap deployment exercise promotion too.
        On an injected fault the old generation keeps serving untouched
        (``InjectedCrash`` propagates — a crash is never handled)."""
        metrics = self.engine.metrics
        with self._swap_lock:
            old = self.engine.store
            act = _chaos_fault(chaos_point)
            if act is not None:
                raise act.to_error()
            self.engine.activate(store)
            self.delta_version = 0  # fresh generation: no deltas yet
            if model_dir is not None:
                floor = store.generation if self.log_owner else self._base[1]
                self._base = (model_dir, floor)
            metrics.inc("swaps")
            logger.info("promote: gen %d (version %r) -> gen %d (version "
                        "%r)", old.generation, old.version,
                        store.generation, store.version)

    def apply_delta(self, cid: str, entity: str, row) -> bool:
        """Scatter one updated coefficient row into the LIVE generation
        (online-learned random effects — no generation flip, no recompile).
        Returns True when applied; False when rejected (unknown entity,
        unknown/fixed coordinate, wrong row width) — a rejected delta
        leaves every coefficient untouched."""
        return self.publish_delta(cid, entity, row) is not None

    def publish_delta(self, cid: str, entity: str, row,
                      ) -> Optional[Tuple[int, int]]:
        """``apply_delta`` that returns the update's
        ``(generation, delta_version)`` identity (None when rejected) and,
        when a delta log is attached to an owning swapper, durably appends
        the record under that identity.  This is the trainer's publish
        sink: apply-then-log under the swap lock, so log order IS apply
        order and the identity pairs with exactly one generation.

        Replicated rows need nothing extra here: the store's
        ``apply_delta`` scatters one payload to EVERY device row holding
        the entity (hot-row replication, coefficient_store module
        docstring) in one snapshot swap, so all replicas carry this
        identity — and the rollback below (re-applying ``prev``) fans out
        the same way, keeping replicas coherent through a failed append."""
        metrics = self.engine.metrics
        with self._swap_lock:
            store = self.engine.store
            prev = None
            if self.delta_log is not None and self.log_owner:
                # snapshot the row we are about to overwrite: if the log
                # append fails the apply must be rolled back (see below)
                c = store.coordinates.get(cid)
                if c is not None and hasattr(c, "dense_row"):
                    eid = store.entity_id(c.random_effect_type, entity)
                    if eid >= 0:
                        prev = c.dense_row(eid)
            try:
                ok = store.apply_delta(cid, entity, row)
            except ValueError as e:
                logger.error("delta rejected (gen %d): %s",
                             store.generation, e)
                ok = False
            if not ok:
                metrics.inc("delta_rejects")
                return None
            self.delta_version += 1
            identity = (store.generation, self.delta_version)
            if self.delta_log is not None and self.log_owner:
                try:
                    self.delta_log.append(DeltaRecord(
                        generation=identity[0], delta_version=identity[1],
                        cid=cid, entity=entity,
                        row=tuple(float(x)
                                  for x in np.asarray(row).ravel())))
                except OSError as e:
                    # Disk degradation: the log is the durable authority —
                    # an unlogged delta must not stay live, or replicas
                    # replaying the log can never reach this state.  Roll
                    # the in-memory apply back, block THIS publish, and
                    # keep serving; the log truncated itself to the last
                    # valid frame, so the next publish retries cleanly
                    # once the disk heals.
                    if prev is not None:
                        store.apply_delta(cid, entity, prev)
                    self.delta_version -= 1
                    metrics.registry.inc("delta_publish_blocked_total",
                                         reason="log_append")
                    logger.error(
                        "delta publish blocked (gen %d): log append "
                        "failed, apply rolled back, serving continues: %s",
                        store.generation, e)
                    return None
            if obs_enabled():
                # remember which trace published this identity: the
                # replication sender stamps it on the wire frame, and the
                # replica's store-visible instant closes the chain
                ctx = ctx_current()
                if ctx is not None:
                    ctx_note_delta(identity, ctx)
            return identity

    def swap_async(self, model_dir: str, version: str = "") -> threading.Thread:
        """Run ``swap`` on a daemon thread; returns the thread (join it to
        observe completion).  Requests keep flowing on the old generation
        until the flip."""
        t = threading.Thread(target=self.swap, args=(model_dir, version),
                             daemon=True, name="photon-serving-swap")
        t.start()
        return t
