"""Online GLMix scoring — the serving half of the Photon ML design.

The reference trains GAME models in Spark and publishes them to PalDB
stores + broadcast coefficients for LinkedIn's online serving stack; this
package is that serving layer, TPU-native:

  - ``coefficient_store``: device-resident versioned coefficient tables
    (the PalDB analog) with a frequency-ranked hot set (EWMA hit counters
    + promotion/demotion rebalancing), an LRU host fallback for cold
    entities, and streaming per-entity delta updates;
  - ``batcher``: request micro-batching padded to a fixed bucket ladder so
    every shape hits an already-compiled executable, plus the async
    deadline accumulator (``AsyncBatcher``: submit one request, get a
    future; flushes on a full bucket or a ~500µs deadline);
  - ``engine``: AOT-lowered per-(signature, bucket) scoring kernels sharing
    the batch path's score composition (game/scoring.py);
  - ``swap``: atomic hot model reload (load -> warm -> flip) and the
    streaming-delta entry point (``(generation, delta_version)`` identity);
  - ``metrics``: the serving metrics facade (latency histograms, QPS,
    padding waste + per-bucket occupancy, hot-set hit rate, entity misses,
    flush mix, swap counters) over the unified ``obs.MetricsRegistry`` —
    JSON snapshot wire format preserved, Prometheus exposition added; the
    hot paths also emit ``obs`` tracer spans (submit → flush → resolve →
    execute) when tracing is on;
  - ``frontend``: the network edge — an asyncio TCP server multiplexing
    many clients into the AsyncBatcher with deadline-budget admission
    control (load shedding + hysteresis), per-client round-robin fairness,
    graceful drain on swap/SIGTERM, a ``/metrics`` scrape endpoint, and
    the open-loop Poisson load generator behind
    ``bench.py --serving --open-loop``;
  - ``fleet``: multi-model serving — a keyed family of model handles
    sharing one AOT kernel cache and one device hot-row budget with
    per-tenant quotas, plus canary rollout (deterministic traffic split,
    auto-promote/auto-rollback) and shadow scoring.

``cli/serve.py`` wires these into a stdin/JSON-lines driver (or, with
``--listen``, the socket front end) and a programmatic ``build_server``
entry point.
"""

from photon_ml_tpu.serving.batcher import (AsyncBatcher, BucketedBatcher,  # noqa: F401
                                           Request, pow2_bucket_ladder,
                                           request_from_json)
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,  # noqa: F401
                                                     HotSetManager,
                                                     StoreConfig)
from photon_ml_tpu.serving.engine import KernelCache, ScoringEngine  # noqa: F401
from photon_ml_tpu.serving.fleet import (CanaryController,  # noqa: F401
                                         CanaryPolicy, ModelFleet,
                                         ModelHandle, ShadowScorer,
                                         TenantBudgetError,
                                         UnknownModelError)
from photon_ml_tpu.serving.metrics import ServingMetrics  # noqa: F401
from photon_ml_tpu.serving.swap import HotSwapper  # noqa: F401
