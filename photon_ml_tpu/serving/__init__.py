"""Online GLMix scoring — the serving half of the Photon ML design.

The reference trains GAME models in Spark and publishes them to PalDB
stores + broadcast coefficients for LinkedIn's online serving stack; this
package is that serving layer, TPU-native:

  - ``coefficient_store``: device-resident versioned coefficient tables
    (the PalDB analog) with an LRU host fallback for cold entities;
  - ``batcher``: request micro-batching padded to a fixed bucket ladder so
    every shape hits an already-compiled executable;
  - ``engine``: AOT-lowered per-(signature, bucket) scoring kernels sharing
    the batch path's score composition (game/scoring.py);
  - ``swap``: atomic hot model reload (load -> warm -> flip);
  - ``metrics``: one thread-safe registry (latency histograms, QPS,
    padding waste, entity misses, swap counters) exported as JSON.

``cli/serve.py`` wires these into a stdin/JSON-lines driver and a
programmatic ``build_server`` entry point.
"""

from photon_ml_tpu.serving.batcher import (BucketedBatcher, Request,  # noqa: F401
                                           pow2_bucket_ladder,
                                           request_from_json)
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,  # noqa: F401
                                                     StoreConfig)
from photon_ml_tpu.serving.engine import ScoringEngine  # noqa: F401
from photon_ml_tpu.serving.metrics import ServingMetrics  # noqa: F401
from photon_ml_tpu.serving.swap import HotSwapper  # noqa: F401
