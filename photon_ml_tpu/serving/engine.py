"""AOT-compiled online scoring engine.

Photon ML reference counterpart: transformers/GameTransformer.scala — score
a prepared dataset with a GameModel by summing per-coordinate scores.  The
online twin differs in three accelerator-driven ways:

  1. **AOT compilation.**  Every (model-shape-signature, bucket-size) pair
     is lowered and compiled ONCE up front (``jax.jit(...).lower(...)
     .compile()``); requests only ever call finished executables, so the
     tail latency of a first-compile (tens of seconds on TPU) can never
     land on a user request.  Per-request input buffers are donated to the
     executable on accelerator backends (the coefficient tables are NOT —
     they are reused across every request of a model generation).
  2. **Bucketed shapes.**  The batcher pads each micro-batch to a fixed
     ladder of bucket sizes, so the executable cache stays small and the
     second-and-later request at any bucket size triggers zero recompiles
     (``compile_count`` exposes this for tests/monitoring).
  3. **Composition parity.**  The kernel composes per-coordinate margins
     with the SAME ``game/scoring.additive_total`` and the same contraction
     primitives (``parallel/bucketing.score_samples``, ``x @ w``) the batch
     path uses, so serving scores are bitwise the ``GameTransformer`` batch
     scores — the property test in tests/test_serving.py holds this line.

Hot swap: ``activate`` flips the generation pointer atomically; requests
already scoring keep the store they snapshotted (serving/swap.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.chaos.injector import fault as _chaos_fault
from photon_ml_tpu.game.scoring import additive_total, output_scores
from photon_ml_tpu.obs import get_probe
from photon_ml_tpu.obs.trace import enabled as obs_enabled
from photon_ml_tpu.obs.trace import span as obs_span
from photon_ml_tpu.obs.watch.attribution import attribute as _attribute
from photon_ml_tpu.parallel.bucketing import score_samples
from photon_ml_tpu.serving.batcher import (AsyncBatcher, BucketedBatcher,
                                           Request, densify_features)
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                     CompactRandomCoordinate,
                                                     FixedCoordinate)
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.utils.logging import Timed

Array = jax.Array


def _cold_margin(x: Array, overflow: Array) -> Array:
    """Cold-entity contribution: the same per-row contraction
    ``score_samples`` applies to device-table rows, on host-gathered rows
    (zeros for hot/unknown samples -> adds exactly 0.0)."""
    return jnp.einsum("nd,nd->n", x, overflow)


class KernelCache:
    """Shared AOT-executable cache: ``(store.signature(), bucket)`` -> exe.

    One engine owns a private cache by default; a ``serving.fleet.ModelFleet``
    hands ONE cache to every per-model engine so same-signature models share
    compiled executables outright and distinct-shape models coexist side by
    side — the compiled-program family stays fixed as tenancy grows.

    Pruning is liveness-based rather than pairwise: each engine registers
    its ACTIVE store's signature under its own identity (``note_live``), and
    ``prune`` drops only keys no live store (plus explicitly kept retiring
    signatures) can ever reach again.  A single-engine cache degenerates to
    exactly the old keep-{old, new} behavior.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._executables: Dict[Tuple, object] = {}
        self.compile_count = 0  # compiles performed into THIS cache
        self._live: Dict[int, Tuple] = {}  # id(owner) -> active signature

    def __len__(self) -> int:
        with self._lock:
            return len(self._executables)

    def note_live(self, owner: object, signature: Tuple) -> None:
        """Record ``owner``'s (an engine's) active-store signature — the
        set of live signatures is what ``prune`` preserves."""
        with self._lock:
            self._live[id(owner)] = signature

    def drop_owner(self, owner: object) -> None:
        """Forget an engine that will never score again (fleet eviction)."""
        with self._lock:
            self._live.pop(id(owner), None)

    def get(self, key: Tuple):
        with self._lock:
            return self._executables.get(key)

    def put(self, key: Tuple, exe: object) -> None:
        with self._lock:
            self._executables[key] = exe
            self.compile_count += 1

    def prune(self, keep_extra: Sequence[Tuple] = ()) -> None:
        """Drop executables no live store can reach.  ``keep_extra`` holds
        retiring signatures in-flight requests may still be scoring on."""
        with self._lock:
            keep = set(self._live.values()) | set(keep_extra)
            self._executables = {k: v for k, v in self._executables.items()
                                 if k[0] in keep}

    def signatures(self) -> Tuple[Tuple, ...]:
        """Distinct signatures currently cached (tests/introspection)."""
        with self._lock:
            return tuple({k[0] for k in self._executables})


class ScoringEngine:
    """Low-latency scorer over a CoefficientStore (see module docstring)."""

    def __init__(self, store: CoefficientStore,
                 batcher: Optional[BucketedBatcher] = None,
                 metrics: Optional[ServingMetrics] = None,
                 kernels: Optional[KernelCache] = None):
        self._store = store
        self.batcher = batcher or BucketedBatcher()
        self.metrics = metrics or ServingMetrics()
        self._lock = threading.Lock()
        # private by default; a ModelFleet passes one shared cache to every
        # per-model engine so same-shape models never compile twice
        self.kernels = kernels or KernelCache()
        self.kernels.note_live(self, store.signature())
        self.compile_count = 0  # compiles THIS engine performed

    # -- generation management (hot swap) ----------------------------------
    @property
    def store(self) -> CoefficientStore:
        return self._store

    def activate(self, store: CoefficientStore) -> CoefficientStore:
        """Atomically flip the serving generation; returns the old store.
        In-flight requests snapshotted the old store and finish on it."""
        with obs_span("serve.activate", generation=store.generation):
            with self._lock:
                old, self._store = self._store, store
            # executables no LIVE store (any engine on this cache) can
            # reach again are dropped so repeated swaps stay bounded; the
            # retiring signature is kept for in-flight requests that
            # snapshotted the old store
            self.kernels.note_live(self, store.signature())
            self.kernels.prune(keep_extra=(old.signature(),))
            self.metrics.inc("activations")
        return old

    # -- compilation -------------------------------------------------------
    def warm(self, buckets: Optional[Sequence[int]] = None,
             store: Optional[CoefficientStore] = None) -> int:
        """Compile executables for ``buckets`` (default: the batcher's whole
        ladder) against ``store`` (default: active).  Returns how many were
        newly compiled.  Hot swap warms the NEW store here before flipping
        the pointer, so no request ever waits on a compile."""
        store = store or self._store
        buckets = tuple(buckets) if buckets is not None \
            else self.batcher.bucket_sizes
        before = self.compile_count
        with Timed(f"serving.warm gen{store.generation}",
                   sink=self.metrics.phase):
            for b in buckets:
                self._executable(store, b)
        return self.compile_count - before

    def _abstract_args(self, store: CoefficientStore, bucket: int):
        """ShapeDtypeStructs matching _concrete_args.  Compact coordinates
        ride the SAME (tables, slots, overflows) argument slots with
        (indices, values) PAIRS as the pytree leaves — one executable
        signature for every coordinate mix."""
        s = jax.ShapeDtypeStruct
        x_dt = np.dtype(store.config.x_dtype)
        xs = {shard: s((bucket, d), x_dt)
              for shard, d in store.shard_dims.items()}
        fixed_ws, tables, slots, overflows = [], [], [], []
        for cid in store.order:
            c = store.coordinates[cid]
            if isinstance(c, FixedCoordinate):
                fixed_ws.append(s(c.weights.shape, c.weights.dtype))
                continue
            # sharded stores pin the hot tables' mesh layout into the AOT
            # signature — lowering bakes the shard-local kernel in, and the
            # executable rejects a mislaid table instead of silently
            # gathering it
            sh = None if c.shard_spec is None else c.shard_spec.sharding
            if isinstance(c, CompactRandomCoordinate):
                hs = c.hot
                tables.append(
                    (s(hs.indices.shape, hs.indices.dtype, sharding=sh),
                     s(hs.values.shape, hs.values.dtype, sharding=sh)))
                slots.append(s((bucket,), np.dtype(np.int32)))
                overflows.append((s((bucket, c.k), np.dtype(np.int32)),
                                  s((bucket, c.k), hs.values.dtype)))
            else:
                tables.append(s(c.table.shape, c.table.dtype, sharding=sh))
                slots.append(s((bucket,), np.dtype(np.int32)))
                overflows.append(s((bucket, c.dim), c.table.dtype))
        return xs, fixed_ws, tables, slots, overflows

    def _build_fn(self, store: CoefficientStore, bucket: int):
        order = list(store.order)
        mesh = store.mesh

        def _kind(c):
            if isinstance(c, FixedCoordinate):
                return "fixed"
            return "compact" if isinstance(c, CompactRandomCoordinate) \
                else "dense"

        # (cid, kind, feature shard, per-shard hot rows | None if unsharded)
        kinds = []
        for cid in order:
            c = store.coordinates[cid]
            local_rows = None
            if getattr(c, "shard_spec", None) is not None:
                rows = (c.hot.indices.shape[0]
                        if isinstance(c, CompactRandomCoordinate)
                        else c.table.shape[0])
                local_rows = rows // c.shard_spec.n_shards
            kinds.append((cid, _kind(c), c.feature_shard, local_rows))

        if mesh is not None:
            # pod-slice kernels: each shard scores ONLY the slots whose
            # global device row lives in its table block, then the psum
            # folds the per-shard partial margins — the [bucket] score
            # vector is the only thing that crosses ICI; coefficient rows
            # never leave their shard (no all-gather, by construction)
            from jax.sharding import PartitionSpec as P

            from photon_ml_tpu.parallel.compat import shard_map
            from photon_ml_tpu.parallel.mesh import SHARD_AXIS

            def _localize(s, cap):
                # global row -> this shard's local row; -1 (scores 0.0 by
                # the kernels' masking contract) for rows owned elsewhere.
                # PLACEMENT-AGNOSTIC: the kernel only asks "is this global
                # row in my block", so traffic-aware routing and hot-row
                # replication (coefficient_store) change WHICH rows hold
                # an entity without touching this path — exactly one shard
                # owns any resolved row and the rest contribute 0.0 to the
                # psum, which is why scores stay bitwise identical under
                # any routing table
                sid = jax.lax.axis_index(SHARD_AXIS)
                loc = s - sid * cap
                mine = (s >= 0) & (loc >= 0) & (loc < cap)
                return jnp.where(mine, loc, -1)

            def _sharded_dense(cap):
                def local_fn(t, s, xx):
                    return jax.lax.psum(
                        score_samples(t, _localize(s, cap), xx), SHARD_AXIS)
                return shard_map(local_fn, mesh=mesh,
                                 in_specs=(P(SHARD_AXIS), P(), P()),
                                 out_specs=P())

            def _sharded_compact(cap):
                def local_fn(ti, tv, s, xx):
                    from photon_ml_tpu.models.game import score_compact_dense
                    return jax.lax.psum(
                        score_compact_dense(ti, tv, _localize(s, cap), xx),
                        SHARD_AXIS)
                return shard_map(
                    local_fn, mesh=mesh,
                    in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P()),
                    out_specs=P())

        def fn(xs, fixed_ws, tables, slots, overflows):
            from photon_ml_tpu.models.game import score_compact_dense

            margins = []
            fi = ri = 0
            for cid, kind, shard, local_rows in kinds:
                x = xs[shard]
                if kind == "fixed":
                    # == models/glm.Coefficients.score (x @ means)
                    margins.append(x @ fixed_ws[fi])
                    fi += 1
                elif kind == "compact":
                    # the SAME compact gather kernel batch scoring uses
                    # (models/game.score_compact_dense) for the hot rows,
                    # and the identical math on per-sample overflow rows
                    # (slots = iota: row i scores its own cold row; dim-
                    # padded hot/unknown rows contribute exactly 0.0)
                    t_idx, t_val = tables[ri]
                    o_idx, o_val = overflows[ri]
                    if local_rows is None:
                        m = score_compact_dense(t_idx, t_val, slots[ri], x)
                    else:
                        m = _sharded_compact(local_rows)(
                            t_idx, t_val, slots[ri], x)
                    # cold rows are host-gathered per sample and replicated;
                    # they stay outside the shard_map
                    cold = score_compact_dense(
                        o_idx, o_val, jnp.arange(bucket, dtype=jnp.int32), x)
                    margins.append(m + cold)
                    ri += 1
                else:
                    if local_rows is None:
                        m = score_samples(tables[ri], slots[ri], x)
                    else:
                        m = _sharded_dense(local_rows)(
                            tables[ri], slots[ri], x)
                    margins.append(m + _cold_margin(x, overflows[ri]))
                    ri += 1
            # the ONE additive composition (game/scoring.py) — shared with
            # GameModel.score so batch and serving totals cannot drift
            return additive_total(bucket, margins)

        return fn

    def _executable(self, store: CoefficientStore, bucket: int):
        key = (store.signature(), bucket)
        exe = self.kernels.get(key)
        if exe is not None:
            return exe
        fn = self._build_fn(store, bucket)
        # donate the per-request buffers (features, slots, overflow) — they
        # are rebuilt every request, so the executable may reuse their
        # device memory for outputs; coefficient tables (argnums 1, 2) live
        # across requests and must NOT be donated.  CPU has no donation
        # support (it would only warn), so gate on backend.
        donate = (0, 3, 4) if jax.default_backend() != "cpu" else ()
        # probe accounting: every AOT compile is counted + timed under the
        # "serving.engine" site, so "did serving recompile after warm" is a
        # registry query that must agree with compile_count
        with get_probe().compile_span("serving.engine", bucket=bucket):
            jitted = jax.jit(fn, donate_argnums=donate)
            lowered = jitted.lower(*self._abstract_args(store, bucket))
            exe = lowered.compile()
        self.kernels.put(key, exe)
        with self._lock:
            self.compile_count += 1
        self.metrics.inc("compiles")
        return exe

    # -- scoring -----------------------------------------------------------
    def score_requests(self, requests: Sequence[Request],
                       predict_mean: bool = False,
                       store: Optional[CoefficientStore] = None) -> np.ndarray:
        """Score a request list; returns one score per request (raw margin +
        offset, or the task's inverse-link mean with ``predict_mean`` — the
        same output contract as cli/score.py).  ``store`` overrides the
        active generation for this call only — canary/shadow scoring
        (serving/fleet) scores a staged store without flipping the
        pointer; executables come from the same ``kernels`` cache."""
        if store is None:
            store = self._store  # snapshot: finish on one generation
        n = len(requests)
        self.metrics.inc("requests", n)
        if n == 0:
            return np.zeros(0)
        out: Optional[np.ndarray] = None
        for mb in self.batcher.plan(n):
            t0 = time.perf_counter()
            act = _chaos_fault("serve.execute")
            if act is not None:
                # chaos: hold the scoring path itself (stall/stall_dist) —
                # the latency-SLO degradation episodes alarm on; requests
                # still succeed, so availability objectives stay quiet.
                # Any other kind at this point is a seam misuse.
                if act.kind in ("stall", "stall_dist"):
                    time.sleep(float(act.data.get("stall_s", 0.05)))
                else:
                    raise act.to_error()
            chunk = requests[mb.start:mb.stop]
            attrs = {}
            if obs_enabled():
                # a chunk scores many requests: stamp every trace id it
                # carries, so the execute (and mesh psum) spans join each
                # request's cross-process timeline — same contract as the
                # batcher's serve.flush span
                tids = sorted({r.ctx[0] for r in chunk
                               if r.ctx is not None})
                if tids:
                    attrs["traces"] = tids
            with obs_span("serve.execute", bucket=mb.bucket,
                          rows=mb.real_rows, **attrs) as sp:
                # photonwatch attribution: split this span into host
                # (dispatch) vs device (drain) time — stamped into the
                # span's attrs and the xla_*_seconds{site=} families
                with _attribute("serve.execute", sp):
                    scores = self._score_chunk(store, chunk, mb.bucket,
                                               trace_attrs=attrs)
            if out is None:
                out = np.empty(n, scores.dtype)
            out[mb.start:mb.stop] = scores[: mb.real_rows]
            self.metrics.observe_batch(mb.bucket, mb.real_rows,
                                       time.perf_counter() - t0)
        raw = out + np.asarray([r.offset for r in requests], out.dtype)
        return output_scores(raw, store.task, predict_mean=predict_mean)

    def _score_chunk(self, store: CoefficientStore,
                     chunk: Sequence[Request], bucket: int,
                     trace_attrs: Optional[dict] = None) -> np.ndarray:
        exe = self._executable(store, bucket)
        xs = densify_features(chunk, store.index_maps, bucket,
                              dtype=store.config.x_dtype)
        fixed_ws, tables, slots, overflows = [], [], [], []
        for cid in store.order:
            c = store.coordinates[cid]
            if isinstance(c, FixedCoordinate):
                fixed_ws.append(c.weights)
            else:
                names = [r.ids.get(c.random_effect_type) for r in chunk]
                # resolve pads rows beyond len(chunk) itself (slot -1, zero
                # overflow, not counted as misses) and returns the residency
                # snapshot the slots index — a concurrent rebalance can
                # never pair these slots with a different table
                tbl, sl, ov = store.resolve(cid, names, n_rows=bucket,
                                            metrics=self.metrics)
                if isinstance(c, CompactRandomCoordinate):
                    # compact snapshot -> the (indices, values) leaf pair
                    # _build_fn's compact branch consumes; overflow is
                    # already the ([n, k], [n, k]) pair
                    tables.append((tbl.indices, tbl.values))
                else:
                    tables.append(tbl)
                slots.append(sl)
                overflows.append(ov)
        if store.mesh is not None:
            # the executable's only cross-shard traffic is the margin psum;
            # trace_attrs carries the chunk's trace ids so the pod-slice
            # hop is attributable to the requests that crossed it
            with obs_span("serve.psum", shards=store.config.mesh_shards,
                          bucket=bucket, **(trace_attrs or {})):
                return np.asarray(exe(xs, fixed_ws, tables, slots, overflows))
        return np.asarray(exe(xs, fixed_ws, tables, slots, overflows))

    # -- async front -------------------------------------------------------
    def async_batcher(self, deadline_s: float = 500e-6,
                      predict_mean: bool = False,
                      flush_threshold: Optional[int] = None) -> AsyncBatcher:
        """An AsyncBatcher feeding this engine: submit requests one at a
        time, get score futures back; flushes on a full top bucket or the
        deadline, whichever first (see serving/batcher.AsyncBatcher)."""

        def score(reqs: Sequence[Request]) -> np.ndarray:
            return self.score_requests(reqs, predict_mean=predict_mean)

        return AsyncBatcher(
            score,
            flush_threshold=flush_threshold or self.batcher.max_batch,
            deadline_s=deadline_s, metrics=self.metrics)
