from photon_ml_tpu.opt.types import SolverConfig, SolverResult  # noqa: F401
from photon_ml_tpu.opt.lbfgs import minimize_lbfgs, minimize_owlqn  # noqa: F401
from photon_ml_tpu.opt.tron import minimize_tron  # noqa: F401
from photon_ml_tpu.opt.constraints import project_to_box, box_arrays  # noqa: F401
from photon_ml_tpu.opt.solve import make_solver  # noqa: F401
