"""Solver configuration / result / state-tracking containers.

Reference: photon-lib .../optimization/Optimizer.scala:36-249 (iteration loop,
convergence reasons, rel->abs tolerance derived from the FIRST state) and
OptimizationStatesTracker.scala (per-iteration value/gradient-norm history).

TPU-first: everything is a statically-shaped pytree so solvers run inside
``lax.while_loop`` and under ``vmap`` (per-entity random-effect solves with
per-lane convergence masks).  The tracker is a pre-allocated [max_iters] array
written with ``.at[iter].set`` — the device-side analog of the reference's
mutable state list.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.types import ConvergenceReason

# Working-precision plateau width, in ulps of the objective value.  Shared
# INVARIANT with opt/linesearch.py's approximate-Wolfe slack: the line
# search may accept a step whose objective is up to PLATEAU_ULPS ulps worse
# than phi0, and convergence_check's function tolerance is floored at the
# same width — so any slack-accepted step is immediately recognized as
# converged and the solver can never creep uphill across iterations.
# Raising the slack without raising the floor would reintroduce the
# plateau-thrashing pathology both exist to prevent.
PLATEAU_ULPS = 4.0

Array = jax.Array


@struct.dataclass
class SolverConfig:
    """Solver hyperparameters.  Static fields shape the compiled program.

    Defaults follow the reference: LBFGS m=10, tol=1e-7, maxIter=100
    (LBFGS.scala:152-157); TRON tol=1e-5, maxIter=15, CG<=20 (TRON.scala:256-262).
    """

    max_iters: int = struct.field(pytree_node=False, default=100)
    tolerance: float = struct.field(pytree_node=False, default=1e-7)
    history: int = struct.field(pytree_node=False, default=10)  # L-BFGS m
    max_linesearch: int = struct.field(pytree_node=False, default=25)
    c1: float = struct.field(pytree_node=False, default=1e-4)  # Armijo
    c2: float = struct.field(pytree_node=False, default=0.9)  # Wolfe curvature
    # TRON (reference TRON.scala:80-253):
    max_cg: int = struct.field(pytree_node=False, default=20)
    track_states: bool = struct.field(pytree_node=False, default=True)

    @classmethod
    def lbfgs_default(cls) -> "SolverConfig":
        return cls(max_iters=100, tolerance=1e-7)

    @classmethod
    def tron_default(cls) -> "SolverConfig":
        return cls(max_iters=15, tolerance=1e-5, max_cg=20)


@struct.dataclass
class StateTracker:
    """Stacked per-iteration history (reference OptimizationStatesTracker).

    ``values[i]`` / ``grad_norms[i]`` are valid for i < num_states; unused
    slots stay at their init sentinel (nan).  Shape [max_iters + 1]: slot 0 is
    the initial state, matching the reference which records the state at the
    initial coefficients before iterating (Optimizer.scala:181).
    """

    values: Array
    grad_norms: Array
    num_states: Array  # int32 scalar

    @classmethod
    def init(cls, max_iters: int, dtype) -> "StateTracker":
        n = max_iters + 1
        return cls(
            values=jnp.full((n,), jnp.nan, dtype),
            grad_norms=jnp.full((n,), jnp.nan, dtype),
            num_states=jnp.zeros((), jnp.int32),
        )

    def record(self, value: Array, grad_norm: Array) -> "StateTracker":
        i = self.num_states
        return StateTracker(
            values=self.values.at[i].set(value),
            grad_norms=self.grad_norms.at[i].set(grad_norm),
            num_states=i + 1,
        )


@struct.dataclass
class SolverResult:
    """Final solver output.

    ``reason`` encodes ConvergenceReason as int32 (device-friendly); use
    ``convergence_reason()`` host-side.
    """

    w: Array
    value: Array
    grad_norm: Array
    iterations: Array  # int32
    reason: Array  # int32 ConvergenceReason
    tracker: Optional[StateTracker] = None

    def convergence_reason(self) -> ConvergenceReason:
        return ConvergenceReason(int(self.reason))


def convergence_check(value, prev_value, init_value, grad_norm, init_grad_norm,
                      iteration, max_iters, tolerance):
    """The reference's convergence logic (Optimizer.scala:135-149), vectorized.

    Tolerances are RELATIVE to the initial state (rel->abs conversion at
    iteration 0, Optimizer.scala:181):
      - FunctionValuesConverged: |f_k - f_{k-1}| <= tol * max(|f_0|, eps)
      - GradientConverged:       ||g_k|| <= tol * max(||g_0||, eps)
      - MaxIterations:           k >= max_iters
    Returns int32 reason (0 = not converged).  Priority order matches the
    reference's check order: function values, gradient, max-iterations.
    """
    eps = jnp.asarray(jnp.finfo(value.dtype).tiny, value.dtype)
    # Working-precision floor: |f_k - f_{k-1}| cannot be resolved below a
    # few ulps of f, so a relative tolerance tighter than that (easy at f32
    # with large n: tol*|f0| ~ 1 ulp of f) makes convergence ulp-LUCK — the
    # unlucky path burns a full max_linesearch of objective passes in a
    # doomed final line search before exiting via OBJECTIVE_NOT_IMPROVING
    # (measured 5x on full-scale glmix2).  The reference runs f64 where
    # tol*|f0| is always far above this floor, so clamping preserves its
    # semantics while making f32 exit deterministically at the plateau.
    ulp = jnp.asarray(jnp.finfo(value.dtype).eps, value.dtype) * jnp.maximum(
        jnp.abs(value), jnp.abs(prev_value))
    f_tol = jnp.maximum(tolerance * jnp.maximum(jnp.abs(init_value), eps),
                        PLATEAU_ULPS * ulp)
    g_tol = tolerance * jnp.maximum(init_grad_norm, eps)
    func_conv = jnp.abs(value - prev_value) <= f_tol
    grad_conv = grad_norm <= g_tol
    max_iter = iteration >= max_iters
    reason = jnp.where(
        func_conv,
        ConvergenceReason.FUNCTION_VALUES_CONVERGED,
        jnp.where(
            grad_conv,
            ConvergenceReason.GRADIENT_CONVERGED,
            jnp.where(max_iter, ConvergenceReason.MAX_ITERATIONS, ConvergenceReason.NOT_CONVERGED),
        ),
    )
    return reason.astype(jnp.int32)


def summarize_solver_results(results, valid_masks=None) -> dict:
    """Aggregate statistics over many (possibly vmapped) solver results.

    Reference: RandomEffectOptimizationTracker.scala:158 — thousands of
    per-entity solves reduce to convergence-reason counts + iteration/loss
    summary stats for the job log.  ``results``: SolverResult or list of
    them (each scalar or batched over lanes); ``valid_masks``: per-result
    boolean lane masks (padded bucket lanes are excluded).
    """
    import numpy as np

    if not isinstance(results, (list, tuple)):
        results = [results]
    its, reasons, values = [], [], []
    for k, res in enumerate(results):
        it = np.atleast_1d(np.asarray(res.iterations))
        rs = np.atleast_1d(np.asarray(res.reason))
        va = np.atleast_1d(np.asarray(res.value))
        mask = np.ones(it.shape, bool)
        if valid_masks is not None and valid_masks[k] is not None:
            mask = np.atleast_1d(np.asarray(valid_masks[k])).astype(bool)
        its.append(it[mask])
        reasons.append(rs[mask])
        values.append(va[mask])
    its = np.concatenate(its) if its else np.zeros(0, np.int32)
    reasons = np.concatenate(reasons) if reasons else np.zeros(0, np.int32)
    values = np.concatenate(values) if values else np.zeros(0)
    if len(its) == 0:
        return {"count": 0}
    return {
        "count": int(len(its)),
        "convergence_reasons": {
            ConvergenceReason(int(r)).name: int((reasons == r).sum())
            for r in np.unique(reasons)
        },
        "iterations": {
            "mean": float(its.mean()), "max": int(its.max()),
            "p50": float(np.percentile(its, 50)),
            "p90": float(np.percentile(its, 90)),
        },
        "final_value": {
            "mean": float(values.mean()),
            "max": float(values.max()), "min": float(values.min()),
        },
    }
