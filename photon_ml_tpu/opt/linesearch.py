"""Strong-Wolfe line search (bracket + zoom) as a single ``lax.while_loop``
state machine — jittable and vmappable.

The reference delegates line search to Breeze's StrongWolfeLineSearch
(optimization/LBFGS.scala:39-108 wraps Breeze LBFGS which owns the search).
On TPU each function evaluation is one fused value+grad pass (psum'd under
SPMD), so the search is written to (a) evaluate at most ``max_evals`` times
with static control flow and (b) carry the full gradient of the best point so
the optimizer never re-evaluates it.

Algorithm: Nocedal & Wright, Algorithms 3.5 (bracketing) / 3.6 (zoom), with a
safeguarded quadratic-interpolation zoom step.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array

_BRACKET, _ZOOM, _DONE, _FAILED = 0, 1, 2, 3


class LineSearchResult(NamedTuple):
    alpha: Array  # accepted step (0.0 on failure)
    phi: Array  # f(w + alpha*d)
    g: Array  # grad f(w + alpha*d)  [d]
    success: Array  # bool: some Armijo-satisfying step found
    wolfe: Array  # bool: strong Wolfe conditions met
    num_evals: Array  # int32


class _State(NamedTuple):
    stage: Array
    i: Array  # eval count
    alpha: Array  # next trial step
    # bracketing history
    alpha_prev: Array
    phi_prev: Array
    # zoom interval
    lo: Array
    hi: Array
    phi_lo: Array
    dphi_lo: Array
    phi_hi: Array
    # best Armijo point so far (its full gradient rides along)
    best_alpha: Array
    best_phi: Array
    best_g: Array
    wolfe: Array


def strong_wolfe(
    phi_fn: Callable[[Array], Tuple[Array, Array]],
    phi0: Array,
    g0: Array,
    d: Array,
    alpha0: Array,
    c1: float = 1e-4,
    c2: float = 0.9,
    max_evals: int = 25,
    max_alpha: float = 1e10,
) -> LineSearchResult:
    """Find alpha satisfying strong Wolfe conditions along direction d.

    phi_fn(alpha) -> (f(w + alpha d), grad f(w + alpha d)).
    phi0/g0: objective value/gradient at alpha=0.
    """
    dtype = phi0.dtype
    dphi0 = jnp.vdot(g0, d).astype(dtype)
    # Approximate-Wolfe slack (Hager & Zhang 2005's remedy, eq. 4.1): near
    # an optimum the available decrease c1*alpha*dphi0 drops below the
    # ROUNDING of phi itself (easy at f32 with large-n objectives, where
    # one ulp of phi0 can exceed any resolvable descent), and the exact
    # Armijo test then fails every trial — burning all max_evals objective
    # passes before the optimizer can conclude OBJECTIVE_NOT_IMPROVING
    # (measured 5x on full-scale glmix2).  Accepting decrease up to
    # PLATEAU_ULPS ulps of phi0 lets the search succeed at the
    # working-precision plateau; the optimizer's convergence check floors
    # its function tolerance at the SAME width (opt/types.PLATEAU_ULPS —
    # see the invariant note there), so the accepted step terminates the
    # solve instead of compounding.
    from photon_ml_tpu.opt.types import PLATEAU_ULPS

    slack = (PLATEAU_ULPS * jnp.asarray(jnp.finfo(dtype).eps, dtype)
             * jnp.abs(phi0))

    def eval_at(alpha):
        phi, g = phi_fn(alpha)
        return phi, g, jnp.vdot(g, d).astype(dtype)

    def armijo_ok(alpha, phi):
        return phi <= phi0 + c1 * alpha * dphi0 + slack

    def curvature_ok(dphi):
        return jnp.abs(dphi) <= -c2 * dphi0

    def bracket_step(s: _State, phi, g, dphi):
        fail_cond = ~armijo_ok(s.alpha, phi) | ((s.i > 0) & (phi >= s.phi_prev))
        curv = curvature_ok(dphi)
        pos = dphi >= 0

        # case 1: Armijo violated (or no decrease) -> zoom(alpha_prev, alpha).
        # phi_lo/dphi_lo describe alpha_prev; its gradient is already in best_g
        # (alpha_prev always satisfied Armijo, or is 0 with best_g = g0).
        z1 = s._replace(
            stage=jnp.int32(_ZOOM),
            lo=s.alpha_prev, hi=s.alpha,
            phi_lo=s.phi_prev, dphi_lo=jnp.where(s.i > 0, s.dphi_lo, dphi0),
            phi_hi=phi,
        )
        # case 2: strong Wolfe satisfied -> done at alpha.
        z2 = s._replace(stage=jnp.int32(_DONE), best_alpha=s.alpha, best_phi=phi,
                        best_g=g, wolfe=jnp.bool_(True))
        # case 3: derivative >= 0 -> zoom(alpha, alpha_prev); alpha is best.
        z3 = s._replace(
            stage=jnp.int32(_ZOOM),
            lo=s.alpha, hi=s.alpha_prev,
            phi_lo=phi, dphi_lo=dphi, phi_hi=s.phi_prev,
            best_alpha=s.alpha, best_phi=phi, best_g=g,
        )
        # case 4: keep expanding; alpha satisfies Armijo and decreases -> best.
        z4 = s._replace(
            alpha=jnp.minimum(2.0 * s.alpha, max_alpha),
            alpha_prev=s.alpha, phi_prev=phi, dphi_lo=dphi,
            best_alpha=s.alpha, best_phi=phi, best_g=g,
        )

        out = jax.tree.map(
            lambda a, b, c, dd: jnp.where(fail_cond, a, jnp.where(curv, b, jnp.where(pos, c, dd))),
            z1, z2, z3, z4,
        )
        return out

    def zoom_step(s: _State, phi, g, dphi):
        # s.alpha is the interpolated trial inside [lo, hi].
        fail_cond = ~armijo_ok(s.alpha, phi) | (phi >= s.phi_lo)
        curv = curvature_ok(dphi)
        flip = dphi * (s.hi - s.lo) >= 0

        # shrink from the hi side
        z1 = s._replace(hi=s.alpha, phi_hi=phi)
        # done
        z2 = s._replace(stage=jnp.int32(_DONE), best_alpha=s.alpha, best_phi=phi,
                        best_g=g, wolfe=jnp.bool_(True))
        # new lo, possibly flipping hi to old lo
        z3 = s._replace(
            lo=s.alpha, phi_lo=phi, dphi_lo=dphi,
            hi=jnp.where(flip, s.lo, s.hi),
            phi_hi=jnp.where(flip, s.phi_lo, s.phi_hi),
            best_alpha=s.alpha, best_phi=phi, best_g=g,
        )
        out = jax.tree.map(
            lambda a, b, c: jnp.where(fail_cond, a, jnp.where(curv, b, c)),
            z1, z2, z3,
        )
        # interval collapse -> stop at best
        tiny = jnp.abs(out.hi - out.lo) <= 1e-12 * jnp.maximum(1.0, jnp.abs(out.hi))
        return out._replace(
            stage=jnp.where((out.stage == _ZOOM) & tiny, jnp.int32(_DONE), out.stage)
        )

    def next_zoom_alpha(s: _State) -> Array:
        """Safeguarded quadratic interpolation using (phi_lo, dphi_lo, phi_hi)."""
        dx = s.hi - s.lo
        denom = 2.0 * (s.phi_hi - s.phi_lo - s.dphi_lo * dx)
        quad = s.lo - s.dphi_lo * dx * dx / jnp.where(denom == 0, 1.0, denom)
        bad = (denom == 0) | ~jnp.isfinite(quad)
        mid = s.lo + 0.5 * dx
        a_min = s.lo + 0.1 * dx
        a_max = s.lo + 0.9 * dx
        safe = jnp.clip(quad, jnp.minimum(a_min, a_max), jnp.maximum(a_min, a_max))
        return jnp.where(bad, mid, safe)

    def body(s: _State) -> _State:
        phi, g, dphi = eval_at(s.alpha)
        s2 = lax.cond(s.stage == _BRACKET,
                      lambda: bracket_step(s, phi, g, dphi),
                      lambda: zoom_step(s, phi, g, dphi))
        s2 = s2._replace(i=s.i + 1)
        # pick the next zoom trial point
        nz = next_zoom_alpha(s2)
        s2 = s2._replace(alpha=jnp.where(s2.stage == _ZOOM, nz, s2.alpha))
        return s2

    def cond(s: _State) -> Array:
        return (s.stage < _DONE) & (s.i < max_evals)

    init = _State(
        stage=jnp.int32(_BRACKET),
        i=jnp.int32(0),
        alpha=jnp.asarray(alpha0, dtype),
        alpha_prev=jnp.zeros((), dtype),
        phi_prev=phi0,
        lo=jnp.zeros((), dtype),
        hi=jnp.zeros((), dtype),
        phi_lo=phi0,
        dphi_lo=dphi0,
        phi_hi=phi0,
        best_alpha=jnp.zeros((), dtype),
        best_phi=phi0,
        best_g=g0,
        wolfe=jnp.bool_(False),
    )
    # Non-descent direction: fail immediately (caller restarts with -g).
    init = init._replace(stage=jnp.where(dphi0 >= 0, jnp.int32(_FAILED), init.stage))

    final = lax.while_loop(cond, body, init)
    success = final.best_alpha > 0
    return LineSearchResult(
        alpha=final.best_alpha,
        phi=final.best_phi,
        g=final.best_g,
        success=success,
        wolfe=final.wolfe,
        num_evals=final.i,
    )
