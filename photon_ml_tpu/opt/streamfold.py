"""Streaming fixed-effect fold: sufficient statistics over the batch stream.

For squared loss the fixed-effect subproblem is ridge regression, whose
sufficient statistics — Gram ``X'WX`` and moment ``X'W(y - offset)`` — are
ADDITIVE over row blocks.  ``StreamingFixedEffectFold`` folds them over the
device-feed batch stream as the ingest uploads each batch, so by the time
the design matrix finishes assembling, the closed-form ridge solution is
one ``d x d`` solve away: an exact squared-loss fixed-effect fit (and a
least-squares warm start for other losses) from the SAME single pass over
the data, no re-read of the assembled matrix.

The accumulate step is ONE jitted program for the whole stream: batch
shape [B, d] is fixed by the feed, and the valid-row count is a traced
scalar (padding rows are masked to weight 0, inert by the core masking
contract) — zero recompiles after the first batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _accum(g, b, x, y, offset, weight, rows):
    mask = (jnp.arange(x.shape[0]) < rows).astype(x.dtype)
    w = weight * mask
    g = g + x.T @ (x * w[:, None])
    b = b + x.T @ (w * (y - offset))
    return g, b


# one program per (B, d, dtype): rows is traced, so every batch — including
# the ragged tail, which keeps the padded [B, d] shape — reuses it
_ACCUM = jax.jit(_accum, donate_argnums=(0, 1))


class StreamingFixedEffectFold:
    """Accumulates ridge sufficient statistics from device-feed batches."""

    def __init__(self, dim: int, l2: float = 0.0, dtype=np.float32):
        self.dim = int(dim)
        self.l2 = float(l2)
        self._g = jnp.zeros((self.dim, self.dim), dtype)
        self._b = jnp.zeros((self.dim,), dtype)
        self.batches = 0
        self.rows = 0

    def accumulate(self, x: jax.Array, y: np.ndarray, offset: np.ndarray,
                   weight: np.ndarray, rows: int) -> None:
        """Fold one batch: ``x`` the [B, d] device block just uploaded by
        the feed (reused, not re-uploaded); scalar columns host slices of
        the batch's ``rows`` valid rows, zero-padded to B here."""
        dt = self._g.dtype
        b_cap = x.shape[0]

        def pad(col, fill=0.0):
            out = np.full(b_cap, fill, dt)
            out[:rows] = np.asarray(col[:rows], dt)
            return jnp.asarray(out)

        self._g, self._b = _ACCUM(
            self._g, self._b, x if x.dtype == dt else x.astype(dt),
            pad(y), pad(offset), pad(weight), rows)
        self.batches += 1
        self.rows += int(rows)

    def solve(self) -> jax.Array:
        """Closed-form ``(X'WX + l2 I)^-1 X'W(y - offset)``."""
        g = self._g + self.l2 * jnp.eye(self.dim, dtype=self._g.dtype)
        return jnp.linalg.solve(g, self._b)

    def gram(self) -> jax.Array:
        return self._g

    def moment(self) -> jax.Array:
        return self._b
