"""L-BFGS and OWLQN as fully-jitted ``lax.while_loop`` solvers.

Reference: photon-lib .../optimization/LBFGS.scala:39-157 (Breeze LBFGS adapter,
m=10, tol=1e-7, maxIter=100) and OWLQN.scala:36-86 (L1 via Breeze OWLQN with a
mutable l1 weight for reg-path sweeps — here the l1 weight is a traced argument,
so sweeps don't recompile).

TPU-first design decisions:
- ONE solver shape for both deployment modes (SURVEY.md §1: the reference runs
  the same Breeze code cluster-wide and executor-local).  Here the closure
  passed as ``value_and_grad`` either psums internally (fixed effect, see
  photon_ml_tpu.parallel) or is vmapped over entity lanes (random effects) —
  ``lax.while_loop`` is vmappable, lanes that converge early mask out.
- Circular [m, d] history buffers with slot masking instead of Breeze's
  deque-of-vectors; the two-loop recursion is a masked ``lax.fori_loop``.
- Strong-Wolfe line search carries the accepted point's gradient, so each
  iteration costs (1 + line-search-evals) fused value+grad passes, identical
  to the reference's per-iteration treeAggregate count.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.opt.constraints import project_to_box
from photon_ml_tpu.opt.linesearch import strong_wolfe
from photon_ml_tpu.opt.types import SolverConfig, SolverResult, StateTracker, convergence_check
from photon_ml_tpu.types import ConvergenceReason

Array = jax.Array
ValueAndGrad = Callable[[Array], Tuple[Array, Array]]


class _LbfgsCarry(NamedTuple):
    w: Array
    f: Array
    g: Array
    s_hist: Array  # [m, d]
    y_hist: Array  # [m, d]
    rho: Array  # [m]
    count: Array  # int32 valid pairs
    pos: Array  # int32 next insert slot
    it: Array  # int32
    reason: Array  # int32
    tracker: StateTracker


def two_loop_direction(g, s_hist, y_hist, rho, count, pos):
    """Masked L-BFGS two-loop recursion over circular buffers.

    Unfilled slots (j >= count) are masked to no-ops so the compiled program
    has static shape regardless of how much history exists yet.
    """
    m = rho.shape[0]

    def newest_first(j):
        return (pos - 1 - j) % m

    def loop1(j, carry):
        q, alphas = carry
        i = newest_first(j)
        valid = j < count
        a = rho[i] * jnp.vdot(s_hist[i], q)
        a = jnp.where(valid, a, 0.0)
        q = q - a * y_hist[i]
        return q, alphas.at[i].set(a)

    q, alphas = lax.fori_loop(0, m, loop1, (g, jnp.zeros_like(rho)))

    # Initial Hessian scaling gamma = s·y / y·y of the newest pair.
    newest = newest_first(0)
    sy = jnp.vdot(s_hist[newest], y_hist[newest])
    yy = jnp.vdot(y_hist[newest], y_hist[newest])
    gamma = jnp.where((count > 0) & (yy > 0), sy / jnp.where(yy == 0, 1.0, yy), 1.0)
    r = gamma * q

    def loop2(j, r):
        jj = m - 1 - j  # oldest first
        i = newest_first(jj)
        valid = jj < count
        b = rho[i] * jnp.vdot(y_hist[i], r)
        upd = (alphas[i] - b) * s_hist[i]
        return r + jnp.where(valid, 1.0, 0.0) * upd

    r = lax.fori_loop(0, m, loop2, r)
    return -r


def minimize_lbfgs(
    value_and_grad: ValueAndGrad,
    w0: Array,
    config: SolverConfig = SolverConfig(),
    box: Optional[Tuple[Array, Array]] = None,
) -> SolverResult:
    """Minimize a smooth objective with L-BFGS + strong Wolfe line search.

    ``box`` = (lower[d], upper[d]) enables a gradient-projection variant
    (the reference's constrained path, OptimizationUtils.
    projectCoefficientsToSubspace, and the LBFGSB use-case — LBFGSB.scala:30-95):
    iterates are clipped into the box, coordinates active at a bound (with the
    gradient pushing outward) are frozen out of the quasi-Newton direction, and
    convergence is measured on the projected gradient ||w - P(w - g)||.
    Projected steps break the Wolfe guarantee, so curvature pairs are admitted
    only when s·y > 0 (cautious update).
    """
    dtype = w0.dtype
    m, d = config.history, w0.shape[-1]

    if box is not None:
        lower, upper = box
        project = project_to_box(lower, upper)

        def opt_gradient(w, g):
            # projected-gradient residual: zero iff w is KKT-stationary
            return w - jnp.clip(w - g, lower, upper)

        def free_mask(w, g):
            active = ((w <= lower) & (g > 0)) | ((w >= upper) & (g < 0))
            return ~active
    else:
        project = None
        opt_gradient = lambda w, g: g
        free_mask = None

    w0 = project(w0) if project is not None else w0
    f0, g0 = value_and_grad(w0)
    g0norm = jnp.linalg.norm(opt_gradient(w0, g0))

    tracker = StateTracker.init(config.max_iters, dtype).record(f0, g0norm)

    init = _LbfgsCarry(
        w=w0, f=f0, g=g0,
        s_hist=jnp.zeros((m, d), dtype),
        y_hist=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        count=jnp.int32(0), pos=jnp.int32(0), it=jnp.int32(0),
        reason=jnp.int32(ConvergenceReason.NOT_CONVERGED),
        tracker=tracker,
    )
    # Degenerate start: already at a stationary point.
    init = init._replace(
        reason=jnp.where(g0norm == 0.0,
                         jnp.int32(ConvergenceReason.GRADIENT_CONVERGED), init.reason)
    )

    def body(c: _LbfgsCarry) -> _LbfgsCarry:
        if free_mask is None:
            g_dir = c.g
        else:
            # Freeze bound-active coordinates out of the direction.
            g_dir = jnp.where(free_mask(c.w, c.g), c.g, 0.0)
        dvec = two_loop_direction(g_dir, c.s_hist, c.y_hist, c.rho, c.count, c.pos)
        if free_mask is not None:
            dvec = jnp.where(free_mask(c.w, c.g), dvec, 0.0)
        dphi0 = jnp.vdot(c.g, dvec)
        # Fall back to steepest descent if the direction lost descent (can
        # happen after projection or a skipped curvature pair).
        bad = dphi0 >= 0
        dvec = jnp.where(bad, -g_dir, dvec)

        gnorm = jnp.linalg.norm(opt_gradient(c.w, c.g))
        alpha0 = jnp.where(c.count == 0,
                           jnp.minimum(1.0, 1.0 / jnp.maximum(gnorm, 1e-12)),
                           jnp.ones((), dtype))

        def phi_fn(alpha):
            wt = c.w + alpha * dvec
            wt = project(wt) if project is not None else wt
            return value_and_grad(wt)

        ls = strong_wolfe(phi_fn, c.f, c.g, dvec, alpha0,
                          c1=config.c1, c2=config.c2, max_evals=config.max_linesearch)

        w_new = c.w + ls.alpha * dvec
        w_new = project(w_new) if project is not None else w_new
        f_new, g_new = ls.phi, ls.g

        s = w_new - c.w
        y = g_new - c.g
        sy = jnp.vdot(s, y)
        admit = ls.success & (sy > 1e-12 * jnp.maximum(jnp.vdot(y, y), 1e-30))
        s_hist = jnp.where(admit, c.s_hist.at[c.pos].set(s), c.s_hist)
        y_hist = jnp.where(admit, c.y_hist.at[c.pos].set(y), c.y_hist)
        rho = jnp.where(admit, c.rho.at[c.pos].set(1.0 / jnp.where(sy == 0, 1.0, sy)), c.rho)
        pos = jnp.where(admit, (c.pos + 1) % m, c.pos)
        count = jnp.where(admit, jnp.minimum(c.count + 1, m), c.count)

        it = c.it + 1
        g_new_norm = jnp.linalg.norm(opt_gradient(w_new, g_new))
        reason = convergence_check(
            f_new, c.f, f0, g_new_norm, g0norm, it, config.max_iters, config.tolerance
        )
        # Line search found no Armijo point: objective can't improve along any
        # direction we can build -> ObjectiveNotImproving (Optimizer.scala's
        # fourth reason; Breeze throws a LineSearchFailed here instead).
        reason = jnp.where(~ls.success, jnp.int32(ConvergenceReason.OBJECTIVE_NOT_IMPROVING), reason)

        keep = ls.success
        return _LbfgsCarry(
            w=jnp.where(keep, w_new, c.w),
            f=jnp.where(keep, f_new, c.f),
            g=jnp.where(keep, g_new, c.g),
            s_hist=s_hist, y_hist=y_hist, rho=rho, count=count, pos=pos,
            it=it, reason=reason,
            tracker=c.tracker.record(jnp.where(keep, f_new, c.f),
                                     jnp.where(keep, g_new_norm, gnorm)),
        )

    def cond(c: _LbfgsCarry) -> Array:
        return c.reason == ConvergenceReason.NOT_CONVERGED

    final = lax.while_loop(cond, body, init)
    return SolverResult(
        w=final.w, value=final.f,
        grad_norm=jnp.linalg.norm(opt_gradient(final.w, final.g)),
        iterations=final.it, reason=final.reason,
        tracker=final.tracker if config.track_states else None,
    )


# ---------------------------------------------------------------------------
# OWLQN — orthant-wise L-BFGS for L1 (reference OWLQN.scala:36-86)
# ---------------------------------------------------------------------------


def _pseudo_gradient(w: Array, g: Array, l1: Array) -> Array:
    """Sub-gradient of f(w) + l1*|w|_1 choosing the steepest orthant at 0."""
    right = g + l1
    left = g - l1
    at_zero = jnp.where(right < 0, right, jnp.where(left > 0, left, 0.0))
    return jnp.where(w > 0, right, jnp.where(w < 0, left, at_zero))


class _OwlqnCarry(NamedTuple):
    w: Array
    f: Array  # smooth part
    g: Array  # smooth gradient
    full_f: Array  # f + l1 term
    s_hist: Array
    y_hist: Array
    rho: Array
    count: Array
    pos: Array
    it: Array
    reason: Array
    tracker: StateTracker


def minimize_owlqn(
    value_and_grad: ValueAndGrad,
    w0: Array,
    l1: Array,
    config: SolverConfig = SolverConfig(),
) -> SolverResult:
    """Minimize smooth(w) + l1*||w||_1 orthant-wise.

    ``l1`` is a traced scalar (or [d] vector with 0 for unpenalized entries,
    e.g. the intercept) — regularization-path sweeps reuse the compiled solver,
    unlike the reference's mutable ``l1RegularizationWeight`` (OWLQN.scala:43).

    Line search: projected backtracking Armijo on the composite objective
    (Breeze OWLQN does the same); curvature history uses smooth gradients.
    """
    dtype = w0.dtype
    m, d = config.history, w0.shape[-1]
    l1 = jnp.asarray(l1, dtype)

    def composite(w, f_smooth):
        return f_smooth + jnp.sum(l1 * jnp.abs(w))

    f0, g0 = value_and_grad(w0)
    pg0 = _pseudo_gradient(w0, g0, l1)
    pg0norm = jnp.linalg.norm(pg0)
    ff0 = composite(w0, f0)
    tracker = StateTracker.init(config.max_iters, dtype).record(ff0, pg0norm)

    init = _OwlqnCarry(
        w=w0, f=f0, g=g0, full_f=ff0,
        s_hist=jnp.zeros((m, d), dtype), y_hist=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        count=jnp.int32(0), pos=jnp.int32(0), it=jnp.int32(0),
        reason=jnp.where(pg0norm == 0.0,
                         jnp.int32(ConvergenceReason.GRADIENT_CONVERGED),
                         jnp.int32(ConvergenceReason.NOT_CONVERGED)),
        tracker=tracker,
    )

    def body(c: _OwlqnCarry) -> _OwlqnCarry:
        pg = _pseudo_gradient(c.w, c.g, l1)
        dvec = two_loop_direction(pg, c.s_hist, c.y_hist, c.rho, c.count, c.pos)
        # Align: zero direction components that leave the pseudo-gradient's
        # descent orthant.
        dvec = jnp.where(dvec * -pg > 0, dvec, 0.0)
        dphi0 = jnp.vdot(pg, dvec)
        bad = dphi0 >= 0
        dvec = jnp.where(bad, -pg, dvec)
        dphi0 = jnp.where(bad, -jnp.vdot(pg, pg), dphi0)

        # Orthant of the trial region: sign(w), or steepest-orthant at 0.
        xi = jnp.where(c.w != 0, jnp.sign(c.w), jnp.sign(-pg))

        pgnorm = jnp.linalg.norm(pg)
        alpha0 = jnp.where(c.count == 0,
                           jnp.minimum(1.0, 1.0 / jnp.maximum(pgnorm, 1e-12)),
                           jnp.ones((), dtype))

        def ls_body(carry):
            alpha, _, _, _, _, k = carry
            wt = c.w + alpha * dvec
            wt = jnp.where(wt * xi >= 0, wt, 0.0)  # orthant projection
            ft, gt = value_and_grad(wt)
            fft = composite(wt, ft)
            ok = fft <= c.full_f + config.c1 * alpha * dphi0
            return (alpha * 0.5, wt, ft, gt, ok, k + 1)

        def ls_cond(carry):
            _, _, _, _, ok, k = carry
            return (~ok) & (k < config.max_linesearch)

        zero_w = jnp.zeros_like(c.w)
        a, w_new, f_new, g_new, ok, _ = lax.while_loop(
            ls_cond, ls_body, (alpha0, zero_w, c.f, c.g, jnp.bool_(False), jnp.int32(0))
        )

        s = w_new - c.w
        y = g_new - c.g
        sy = jnp.vdot(s, y)
        admit = ok & (sy > 1e-12 * jnp.maximum(jnp.vdot(y, y), 1e-30))
        s_hist = jnp.where(admit, c.s_hist.at[c.pos].set(s), c.s_hist)
        y_hist = jnp.where(admit, c.y_hist.at[c.pos].set(y), c.y_hist)
        rho = jnp.where(admit, c.rho.at[c.pos].set(1.0 / jnp.where(sy == 0, 1.0, sy)), c.rho)
        pos = jnp.where(admit, (c.pos + 1) % m, c.pos)
        count = jnp.where(admit, jnp.minimum(c.count + 1, m), c.count)

        ff_new = composite(w_new, f_new)
        it = c.it + 1
        pg_new = _pseudo_gradient(w_new, g_new, l1)
        pg_new_norm = jnp.linalg.norm(pg_new)
        reason = convergence_check(
            ff_new, c.full_f, ff0, pg_new_norm, pg0norm, it, config.max_iters, config.tolerance
        )
        reason = jnp.where(~ok, jnp.int32(ConvergenceReason.OBJECTIVE_NOT_IMPROVING), reason)

        return _OwlqnCarry(
            w=jnp.where(ok, w_new, c.w),
            f=jnp.where(ok, f_new, c.f),
            g=jnp.where(ok, g_new, c.g),
            full_f=jnp.where(ok, ff_new, c.full_f),
            s_hist=s_hist, y_hist=y_hist, rho=rho, count=count, pos=pos,
            it=it, reason=reason,
            tracker=c.tracker.record(jnp.where(ok, ff_new, c.full_f),
                                     jnp.where(ok, pg_new_norm, pgnorm)),
        )

    def cond(c: _OwlqnCarry) -> Array:
        return c.reason == ConvergenceReason.NOT_CONVERGED

    final = lax.while_loop(cond, body, init)
    pg_fin = _pseudo_gradient(final.w, final.g, l1)
    return SolverResult(
        w=final.w, value=final.full_f, grad_norm=jnp.linalg.norm(pg_fin),
        iterations=final.it, reason=final.reason,
        tracker=final.tracker if config.track_states else None,
    )
