"""Batched exact-Newton solver for NARROW random-effect lanes, in
structure-of-arrays ([d, L]) layout.

Why this exists (TPU layout): the generic random-effect path is
``jax.vmap(solve)`` over entity lanes, whose solver state is [L, d] (and
[L, m, d] L-BFGS history).  TPU tiling pads an array's trailing axis to
128 lanes, so at d=4 every state array occupies 32x its logical HBM bytes
and the vmapped while-loop becomes a padded-state bandwidth burn: profiled
on a real v5e, the RE solve loop was 3.06s of a 4.84s glmix_chip sweep at
13% HBM utilization (TPU_PROFILE/, round 5).  Samples-on-lanes [d, L]
arrays pad d only up to the 8-sublane tile (2x at d=4, 1x at d>=8), and
every per-lane reduction is a sublane sum — no dot_general, no transposes,
no padded intermediates.

Why NEWTON: at d <= 16 the exact per-lane Hessian is d(d+1)/2 weighted
column products (cheap, one fused pass over the bucket) and its Cholesky
factorization unrolls into elementwise-over-[L] ops that XLA fuses into a
single kernel.  Newton with Armijo backtracking reaches the reference
tolerance in ~5-10 iterations where L-BFGS takes tens — fewer iterations
x less traffic per iteration.  The OPTIMUM is the same: these per-entity
objectives (pointwise loss + l2/2 ||w||^2, l2 > 0 on every real config)
are strictly convex, so LBFGS / TRON / Newton agree to solver tolerance
(property-tested against the vmapped path in tests/test_optimizers.py).

Reference parity: solves the same per-entity problem as the reference's
SingleNodeOptimizationProblem (photon-api .../optimization/
SingleNodeOptimizationProblem.scala) under the same convergence contract
(opt/types.convergence_check — function values, then gradient, then max
iterations, rel->abs tolerances).  The reference never specializes for
narrow entities; this module is the TPU-native answer to its per-entity
solve loop.

Gating (game/coordinate.py::_bind_solver): decided on SOLVE-space shapes
— plain dense buckets, compact sparse buckets, and INDEX_MAP/RANDOM-
projected buckets (their compact/projected width is exactly where narrow
dims live) all qualify when there are no per-lane normalization/box
extras, l1 == 0, solve dim <= _MAX_SOA_DIM, cap*d^2/2 is small enough,
and the loss is smooth.  Everything else keeps the general vmapped path.
Escape hatch: PHOTON_DISABLE_SOA_NEWTON=1.
"""

from __future__ import annotations

import os
from typing import List

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.core.losses import PointwiseLoss
from photon_ml_tpu.opt.types import SolverConfig, SolverResult, convergence_check
from photon_ml_tpu.types import ConvergenceReason

Array = jax.Array

_MAX_SOA_DIM = 16   # Cholesky unroll is O(d^3) fused ops; 16 covers every
# GLMix random-effect shard in the bench suite (d_user=16, d_item=16, d=4).
# d=32 was tried and reverted: the unroll compiles ~35s (measured, XLA
# CPU) and under the cap*d^2/2 traffic guard only cap<=2 buckets would
# ever qualify at that width — compile cost without a measurable win
# (the 1M-entity cap4xd32 demo shape sits just past the guard, and an
# end-to-end A/B there showed no speedup worth the compile).


def soa_eligible(dim: int, loss_name: str) -> bool:
    """Static part of the gate (the caller adds its own layout conditions)."""
    if os.environ.get("PHOTON_DISABLE_SOA_NEWTON") == "1":
        return False
    return dim <= _MAX_SOA_DIM and loss_name != "smoothed_hinge"


def _cholesky_solve_soa(hh: List[List[Array]], g: Array, jitter: Array) -> Array:
    """x = (H + jitter*I)^-1 g, unrolled over the static d.

    ``hh[i][j]`` (j <= i) are the lower-triangle Hessian entries, each an
    [L] array; ``g`` is [d, L].  Every operation below is elementwise over
    lanes — XLA fuses the whole factorization + two triangular solves into
    one kernel with no [L, d, d] array ever materialized.
    """
    d = g.shape[0]
    lo = [[None] * d for _ in range(d)]
    for i in range(d):
        s = hh[i][i] + jitter
        for k in range(i):
            s = s - lo[i][k] * lo[i][k]
        lii = jnp.sqrt(jnp.maximum(s, jitter))
        lo[i][i] = lii
        for j in range(i + 1, d):
            s2 = hh[j][i]
            for k in range(i):
                s2 = s2 - lo[j][k] * lo[i][k]
            lo[j][i] = s2 / lii
    z = [None] * d
    for i in range(d):
        s = g[i]
        for k in range(i):
            s = s - lo[i][k] * z[k]
        z[i] = s / lo[i][i]
    x = [None] * d
    for i in reversed(range(d)):
        s = z[i]
        for k in range(i + 1, d):
            s = s - lo[k][i] * x[k]
        x[i] = s / lo[i][i]
    return jnp.stack(x)


def _margins(w: Array, x_t: Array, off_t: Array) -> Array:
    """[cap, L] margins: sum over the d sublane axis, no dot_general."""
    acc = jnp.promote_types(x_t.dtype, w.dtype)
    return (x_t.astype(acc) * w[None].astype(acc)).sum(axis=1) + off_t


def _value(loss: PointwiseLoss, w, x_t, y_t, off_t, wt_t, l2) -> Array:
    z = _margins(w, x_t, off_t)
    return (wt_t * loss.loss(z, y_t)).sum(0) + 0.5 * l2 * (w * w).sum(0)


def _value_grad(loss: PointwiseLoss, w, x_t, y_t, off_t, wt_t, l2):
    z = _margins(w, x_t, off_t)
    l, d1 = loss.loss_and_d1(z, y_t)
    f = (wt_t * l).sum(0) + 0.5 * l2 * (w * w).sum(0)
    r = wt_t * d1                                     # [cap, L]
    acc = r.dtype
    g = (x_t.astype(acc) * r[:, None, :]).sum(0) + l2 * w   # [d, L]
    return f, g


def _hess(loss: PointwiseLoss, w, x_t, y_t, off_t, wt_t, l2):
    """Lower-triangle Hessian entries hh[i][j] as [L] arrays — the dominant
    per-iteration cost (d(d+1)/2 weighted column products), computed exactly
    once per Newton iteration."""
    z = _margins(w, x_t, off_t)
    q = wt_t * loss.d2(z, y_t)                        # [cap, L]
    acc = q.dtype
    d = w.shape[0]
    xq = x_t.astype(acc) * q[:, None, :]              # [cap, d, L]
    hh = [[None] * d for _ in range(d)]
    for i in range(d):
        for j in range(i + 1):
            hij = (xq[:, i, :] * x_t[:, j, :].astype(acc)).sum(0)
            if i == j:
                hij = hij + l2
            hh[i][j] = hij
            hh[j][i] = hij
    return hh


def solve_newton_soa(loss: PointwiseLoss, w0_t: Array, x_t: Array,
                     y_t: Array, off_t: Array, wt_t: Array, l2: Array,
                     config: SolverConfig) -> SolverResult:
    """Per-lane Newton descent; all arrays lanes-last.

    w0_t: [d, L] start; x_t: [cap, d, L]; y/off/wt_t: [cap, L]; l2: [L]
    (per-lane traced regularization — lambda sweeps reuse the compile).
    Returns SolverResult with lanes-last ``w`` ([d, L]); the caller
    transposes at its boundary.
    """
    d, num_l = w0_t.shape
    dtype = w0_t.dtype
    c1 = jnp.asarray(config.c1, dtype)
    tol = jnp.asarray(config.tolerance, dtype)
    # Pallas fast path for the step (TPU, lane-aligned buckets): margins ->
    # curvature -> Hessian triangle -> Cholesky solve in ONE kernel, the
    # design streamed through VMEM once per iteration and the [cap, d, L]
    # xq intermediate never materialized (ops/soa_newton.py; same algorithm,
    # parity-tested in interpret mode; PHOTON_SOA_DISABLE_PALLAS=1 escape).
    from photon_ml_tpu.ops import soa_newton

    use_pallas = soa_newton.eligible(d, num_l)

    def gnorm(g):
        # L2 norm, matching the vmapped L-BFGS/TRON convergence inputs
        return jnp.sqrt((g * g).sum(axis=0))

    f0, g0 = _value_grad(loss, w0_t, x_t, y_t, off_t, wt_t, l2)
    gn0 = gnorm(g0)
    # the scale-relative Cholesky floor: keeps padded / weightless lanes
    # (H = l2 I, possibly l2 = 0) factorizable without biasing real steps
    jitter = jnp.asarray(jnp.finfo(dtype).eps, dtype)
    not_improving = jnp.asarray(
        int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING), jnp.int32)

    def cond(state):
        _, _, _, reason, _, k = state
        return jnp.logical_and(k < config.max_iters,
                               jnp.any(reason == 0))

    def body(state):
        # (f, g) ride the carry so the gradient pass runs once per
        # iteration and the Hessian assembly — the dominant cost — exactly
        # once too
        w, f, g, reason, iters, k = state
        active = reason == 0
        if use_pallas:
            step = soa_newton.newton_step(loss, w, g, x_t, y_t, off_t,
                                          wt_t, l2)
        else:
            hh = _hess(loss, w, x_t, y_t, off_t, wt_t, l2)
            step = _cholesky_solve_soa(
                hh, g, jitter * (jnp.abs(jnp.stack([hh[i][i]
                                                    for i in range(d)])).max(0)
                                 + jnp.asarray(1.0, dtype)))
        gd = (g * step).sum(0)                     # descent rate, [L] >= 0

        def ls_cond(ls):
            alpha, accepted, t = ls
            return jnp.logical_and(t < config.max_linesearch,
                                   jnp.any(jnp.logical_and(active,
                                                           ~accepted)))

        def ls_body(ls):
            alpha, accepted, t = ls
            f_try = _value(loss, w - alpha[None] * step,
                           x_t, y_t, off_t, wt_t, l2)
            ok = f_try <= f - c1 * alpha * gd      # False for NaN f_try
            newly = jnp.logical_and(~accepted, ok)
            accepted = jnp.logical_or(accepted, newly)
            alpha = jnp.where(accepted, alpha, alpha * 0.5)
            return alpha, accepted, t + 1

        alpha0 = jnp.ones((num_l,), dtype)
        alpha, accepted, _ = lax.while_loop(
            ls_cond, ls_body,
            (alpha0, jnp.zeros((num_l,), bool), jnp.asarray(0, jnp.int32)))
        # a rejected line search KEEPS the iterate (never w - 0*step: with a
        # non-finite step that is 0*inf = NaN and would poison the lane —
        # the generic solvers keep w on line-search failure too)
        stepped = jnp.logical_and(active, accepted)
        w_new = jnp.where(stepped[None], w - alpha[None] * step, w)
        f_new, g_new = _value_grad(loss, w_new, x_t, y_t, off_t, wt_t, l2)
        r_new = convergence_check(f_new, f, f0, gnorm(g_new), gn0,
                                  k + 1, config.max_iters, tol)
        # line-search exhaustion is a stall, not convergence — the same
        # OBJECTIVE_NOT_IMPROVING the vmapped L-BFGS/TRON paths report
        r_new = jnp.where(jnp.logical_and(active, ~accepted),
                          not_improving, r_new)
        reason = jnp.where(active, r_new, reason)
        w = jnp.where(active[None], w_new, w)
        f_out = jnp.where(active, f_new, f)
        g_out = jnp.where(active[None], g_new, g)
        iters = jnp.where(active, iters + 1, iters)
        return w, f_out, g_out, reason, iters, k + 1

    init = (w0_t, f0, g0,
            jnp.zeros((num_l,), jnp.int32), jnp.zeros((num_l,), jnp.int32),
            jnp.asarray(0, jnp.int32))
    w, f, g, reason, iters, _ = lax.while_loop(cond, body, init)
    return SolverResult(w=w, value=f, grad_norm=gnorm(g),
                        iterations=iters, reason=reason, tracker=None)
