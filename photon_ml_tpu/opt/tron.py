"""TRON: trust-region Newton with truncated conjugate-gradient inner solver.

Reference: photon-lib .../optimization/TRON.scala:80-338 (itself a LIBLINEAR
port): truncated CG (<= 20 iterations, forcing tolerance xi=0.1), trust-region
update with (eta0, eta1, eta2) = (1e-4, 0.25, 0.75) and
(sigma1, sigma2, sigma3) = (0.25, 0.5, 4), and up to 5 consecutive
improvement-failure retries.

TPU shape: both loops are ``lax.while_loop``s; each CG step costs one
Hessian-vector product (a fused double pass, psum'd under SPMD — exactly the
reference's "one treeAggregate per CG step", TRON.scala:293-335).  Vmappable
for per-entity random-effect solves.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.opt.types import SolverConfig, SolverResult, StateTracker, convergence_check
from photon_ml_tpu.types import ConvergenceReason

Array = jax.Array

ETA0, ETA1, ETA2 = 1e-4, 0.25, 0.75
SIGMA1, SIGMA2, SIGMA3 = 0.25, 0.5, 4.0
XI = 0.1  # CG forcing tolerance (TRON.scala truncatedConjugateGradientMethod)
MAX_IMPROVEMENT_FAILURES = 5


class _CgCarry(NamedTuple):
    p: Array  # current step
    r: Array  # residual
    d: Array  # search direction
    rr: Array  # r·r
    it: Array
    done: Array
    hit_boundary: Array


def _truncated_cg(hvp: Callable[[Array], Array], g: Array, delta: Array,
                  max_cg: int) -> Tuple[Array, Array]:
    """Approximately solve H p = -g inside the trust region ||p|| <= delta.

    Returns (p, Hp) — Hp is needed for the predicted-reduction formula.
    """
    dtype = g.dtype
    gnorm = jnp.linalg.norm(g)
    tol = XI * gnorm

    p0 = jnp.zeros_like(g)
    r0 = -g
    init = _CgCarry(p=p0, r=r0, d=r0, rr=jnp.vdot(r0, r0),
                    it=jnp.int32(0), done=gnorm <= tol, hit_boundary=jnp.bool_(False))

    def body(c: _CgCarry) -> _CgCarry:
        hd = hvp(c.d)
        dhd = jnp.vdot(c.d, hd)
        # Non-positive curvature along d: march to the boundary.
        alpha = jnp.where(dhd > 0, c.rr / jnp.where(dhd == 0, 1.0, dhd), jnp.inf)
        p_try = c.p + jnp.where(jnp.isfinite(alpha), alpha, 0.0) * c.d

        crosses = (jnp.linalg.norm(p_try) >= delta) | ~jnp.isfinite(alpha) | (dhd <= 0)

        # tau >= 0 solving ||p + tau*d|| = delta (boundary intersection).
        pd = jnp.vdot(c.p, c.d)
        dd = jnp.vdot(c.d, c.d)
        pp = jnp.vdot(c.p, c.p)
        disc = pd * pd + dd * (delta * delta - pp)
        tau = (-pd + jnp.sqrt(jnp.maximum(disc, 0.0))) / jnp.where(dd == 0, 1.0, dd)
        p_bound = c.p + tau * c.d

        p_new = jnp.where(crosses, p_bound, p_try)
        r_new = c.r - jnp.where(crosses, tau, alpha) * hd
        rr_new = jnp.vdot(r_new, r_new)
        beta = rr_new / jnp.where(c.rr == 0, 1.0, c.rr)
        d_new = r_new + beta * c.d

        done = crosses | (jnp.sqrt(rr_new) <= tol)
        return _CgCarry(p=p_new, r=r_new, d=d_new, rr=rr_new,
                        it=c.it + 1, done=done, hit_boundary=crosses)

    def cond(c: _CgCarry) -> Array:
        return (~c.done) & (c.it < max_cg)

    final = lax.while_loop(cond, body, init)
    # Hp = -g - r  (since r = -g - Hp by CG invariant)
    hp = -g - final.r
    return final.p, hp


class _TronCarry(NamedTuple):
    w: Array
    f: Array
    g: Array
    delta: Array
    it: Array
    failures: Array  # consecutive rejected steps
    reason: Array
    tracker: StateTracker


def minimize_tron(
    value_and_grad: Callable[[Array], Tuple[Array, Array]],
    hvp_at: Callable[[Array, Array], Array],
    w0: Array,
    config: SolverConfig = SolverConfig.tron_default(),
) -> SolverResult:
    """Minimize a twice-differentiable objective with trust-region Newton.

    ``hvp_at(w, v)`` evaluates the Hessian-vector product at w.
    """
    dtype = w0.dtype
    f0, g0 = value_and_grad(w0)
    g0norm = jnp.linalg.norm(g0)
    tracker = StateTracker.init(config.max_iters, dtype).record(f0, g0norm)

    init = _TronCarry(
        w=w0, f=f0, g=g0, delta=g0norm, it=jnp.int32(0), failures=jnp.int32(0),
        reason=jnp.where(g0norm == 0.0,
                         jnp.int32(ConvergenceReason.GRADIENT_CONVERGED),
                         jnp.int32(ConvergenceReason.NOT_CONVERGED)),
        tracker=tracker,
    )

    def body(c: _TronCarry) -> _TronCarry:
        p, hp = _truncated_cg(lambda v: hvp_at(c.w, v), c.g, c.delta, config.max_cg)

        w_try = c.w + p
        f_try, g_try = value_and_grad(w_try)
        actual = c.f - f_try
        gs = jnp.vdot(c.g, p)
        predicted = -(gs + 0.5 * jnp.vdot(p, hp))
        ratio = actual / jnp.where(predicted == 0, 1.0, predicted)

        snorm = jnp.linalg.norm(p)
        # LIBLINEAR-style radius update (TRON.scala:180-215).
        denom = f_try - c.f - gs
        alpha = jnp.where(denom <= 0, SIGMA3, jnp.maximum(SIGMA1, -0.5 * (gs / jnp.where(denom == 0, 1.0, denom))))
        delta = jnp.where(
            ratio < ETA0,
            jnp.minimum(jnp.maximum(alpha, SIGMA1) * snorm, SIGMA2 * c.delta),
            jnp.where(
                ratio < ETA1,
                jnp.maximum(SIGMA1 * c.delta, jnp.minimum(alpha * snorm, SIGMA2 * c.delta)),
                jnp.where(
                    ratio < ETA2,
                    jnp.maximum(SIGMA1 * c.delta, jnp.minimum(alpha * snorm, SIGMA3 * c.delta)),
                    jnp.maximum(c.delta, jnp.minimum(alpha * snorm, SIGMA3 * c.delta)),
                ),
            ),
        )

        accept = (ratio > ETA0) & (actual > 0)
        w_new = jnp.where(accept, w_try, c.w)
        f_new = jnp.where(accept, f_try, c.f)
        g_new = jnp.where(accept, g_try, c.g)
        failures = jnp.where(accept, 0, c.failures + 1).astype(jnp.int32)

        it = c.it + 1
        g_new_norm = jnp.linalg.norm(g_new)
        reason = convergence_check(
            f_new, c.f, f0, g_new_norm, g0norm, it, config.max_iters, config.tolerance
        )
        # Only accepted steps can claim FunctionValuesConverged (a rejected
        # step has f_new == c.f trivially); rejected steps either retry or
        # give up after MAX_IMPROVEMENT_FAILURES (TRON.scala improvement-
        # failure counter).
        reason = jnp.where(
            accept,
            reason,
            jnp.where(
                failures >= MAX_IMPROVEMENT_FAILURES,
                jnp.int32(ConvergenceReason.OBJECTIVE_NOT_IMPROVING),
                jnp.where(it >= config.max_iters,
                          jnp.int32(ConvergenceReason.MAX_ITERATIONS),
                          jnp.int32(ConvergenceReason.NOT_CONVERGED)),
            ),
        )

        return _TronCarry(
            w=w_new, f=f_new, g=g_new, delta=delta, it=it, failures=failures,
            reason=reason,
            tracker=c.tracker.record(f_new, g_new_norm),
        )

    def cond(c: _TronCarry) -> Array:
        return c.reason == ConvergenceReason.NOT_CONVERGED

    final = lax.while_loop(cond, body, init)
    return SolverResult(
        w=final.w, value=final.f, grad_norm=jnp.linalg.norm(final.g),
        iterations=final.it, reason=final.reason,
        tracker=final.tracker if config.track_states else None,
    )
