"""Box-constraint projection.

Reference: photon-lib .../optimization/OptimizationUtils.scala:71
(``projectCoefficientsToSubspace`` with ``constraintMap: Map[Int, (lo, hi)]``)
and LBFGSB.scala:30-95 (box-constrained LBFGS used by the GP kernel fit).

TPU shape: the sparse Map[Int, (lo, hi)] becomes a dense ([d], [d]) pair of
(lower, upper) arrays with ±inf for unconstrained entries — one fused clip.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.types import ConstraintMap

Array = jax.Array


def box_arrays(constraint_map: Optional[ConstraintMap], dim: int, dtype=np.float32
               ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Densify a {feature index: (lo, hi)} map into (lower[d], upper[d])."""
    if not constraint_map:
        return None
    lower = np.full((dim,), -np.inf, dtype)
    upper = np.full((dim,), np.inf, dtype)
    for idx, (lo, hi) in constraint_map.items():
        if not 0 <= idx < dim:
            raise ValueError(f"constraint index {idx} out of range [0, {dim})")
        if lo > hi:
            raise ValueError(f"constraint lo > hi at index {idx}: ({lo}, {hi})")
        lower[idx] = lo
        upper[idx] = hi
    return lower, upper


def project_to_box(lower: Array, upper: Array) -> Callable[[Array], Array]:
    """Return a projection w -> clip(w, lower, upper) for solver use."""

    def project(w: Array) -> Array:
        return jnp.clip(w, lower, upper)

    return project
