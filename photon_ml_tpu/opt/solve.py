"""Solver factory binding a GLMObjective + optimizer choice into a jittable
``solve(w0, batch) -> SolverResult`` function, plus coefficient-variance
computation.

Reference: OptimizerFactory.scala:80, GeneralizedLinearOptimizationProblem.scala:173,
DistributedOptimizationProblem.scala:46-217 (variance: 84-108 — SIMPLE is
1/diag(H), FULL is diag(H^-1) via Cholesky, Linalg.choleskyInverse:104).

The returned ``solve`` is the SINGLE kernel reused in both deployment shapes
(SURVEY.md §1): jit it plainly (or shard_map its objective) for the fixed
effect; ``jax.vmap(solve)`` over padded entity buckets for random effects.
The reference selects OWLQN automatically when L1 regularization is present
(LBFGS.scala init) — same rule here.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from photon_ml_tpu.core.batch import Batch
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.opt.lbfgs import minimize_lbfgs, minimize_owlqn
from photon_ml_tpu.opt.tron import minimize_tron
from photon_ml_tpu.opt.types import SolverConfig, SolverResult
from photon_ml_tpu.types import OptimizerType, VarianceComputationType
from photon_ml_tpu.utils.linalg import cholesky_inverse

Array = jax.Array


def check_box_support(optimizer: OptimizerType, has_l1: bool) -> None:
    """Box constraints are a projected-gradient L-BFGS feature (reference
    OptimizationUtils.projectCoefficientsToSubspace applies them in LBFGSB
    only); TRON and the L1/OWLQN regime refuse.  Shared by make_solver and
    callers that pass per-call boxes to an unboxed-at-build solver."""
    if optimizer == OptimizerType.TRON:
        raise ValueError("TRON does not support box constraints")
    if optimizer == OptimizerType.OWLQN or has_l1:
        raise ValueError("OWLQN does not support box constraints")


def make_solver(
    objective: GLMObjective,
    optimizer: OptimizerType = OptimizerType.LBFGS,
    config: Optional[SolverConfig] = None,
    box: Optional[Tuple[Array, Array]] = None,
) -> Callable[[Array, Batch], SolverResult]:
    """Build solve(w0, batch) for one GLM coordinate.

    ``box``: optional (lower[d], upper[d]) constraint arrays
    (reference constrained-coefficients path, OptimizationUtils.scala).

    The returned callable accepts an optional ``objective=`` override with the
    SAME static structure (loss, fused) but different reg/norm leaves — under
    one ``jax.jit`` this makes regularization-path sweeps recompile-free
    (the reference mutates ``l1RegularizationWeight``/L2 mixins in place for
    the same reason, DistributedOptimizationProblem.updateRegularizationWeight
    :64-75).  The optimizer/L1 dispatch below stays keyed to the λ=build-time
    reg, so an override must not move between the smooth and L1 regimes.
    """
    if config is None:
        config = SolverConfig.tron_default() if optimizer == OptimizerType.TRON else SolverConfig.lbfgs_default()
    has_l1 = objective.reg.l1 > 0.0

    if optimizer == OptimizerType.TRON and has_l1:
        raise ValueError("TRON does not support L1 regularization (reference parity)")
    if box is not None:
        check_box_support(optimizer, has_l1)
    if optimizer == OptimizerType.OWLQN or (optimizer == OptimizerType.LBFGS and has_l1):

        def solve_owlqn(w0: Array, batch: Batch,
                        objective: GLMObjective = objective) -> SolverResult:
            vg = lambda w: objective.value_and_grad(w, batch)
            return minimize_owlqn(vg, w0, objective.reg.l1, config)

        return solve_owlqn

    if optimizer == OptimizerType.LBFGS:

        def solve_lbfgs(w0: Array, batch: Batch,
                        objective: GLMObjective = objective,
                        box: Optional[Tuple[Array, Array]] = box) -> SolverResult:
            # ``box`` is per-call overridable like ``objective`` (same static
            # presence rule): the random-effect coordinate passes per-lane
            # bound arrays through vmap for compact-space constrained solves.
            vg = lambda w: objective.value_and_grad(w, batch)
            return minimize_lbfgs(vg, w0, config, box=box)

        return solve_lbfgs

    if optimizer == OptimizerType.TRON:

        def solve_tron(w0: Array, batch: Batch,
                       objective: GLMObjective = objective) -> SolverResult:
            vg = lambda w: objective.value_and_grad(w, batch)
            hvp_at = lambda w, v: objective.hvp(w, batch, v)
            return minimize_tron(vg, hvp_at, w0, config)

        return solve_tron

    raise ValueError(f"unknown optimizer {optimizer!r}")


def compute_variances(
    objective: GLMObjective,
    w: Array,
    batch: Batch,
    kind: VarianceComputationType,
) -> Optional[Array]:
    """Coefficient variances (reference DistributedOptimizationProblem.scala:84-108).

    SIMPLE: 1 / diag(H)  (NOT the inverse-Hessian diagonal — reference parity).
    FULL:   diag(H^-1) via Cholesky (reference Linalg.choleskyInverse:104).
    """
    if kind == VarianceComputationType.NONE:
        return None
    if kind == VarianceComputationType.SIMPLE:
        d = objective.hessian_diag(w, batch)
        return 1.0 / jnp.where(d == 0, jnp.inf, d)
    if kind == VarianceComputationType.FULL:
        h = objective.hessian(w, batch)
        return jnp.diagonal(cholesky_inverse(h))
    raise ValueError(f"unknown variance computation type {kind!r}")
