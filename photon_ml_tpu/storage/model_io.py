"""GAME model persistence in the reference's on-disk layout.

Reference: photon-client .../data/avro/ModelProcessingUtils.scala:59-625 —
  <dir>/metadata.json                      (model-level metadata)
  <dir>/fixed-effect/<coord>/coefficients.avro   (one BayesianLinearModelAvro)
  <dir>/random-effect/<coord>/part-00000.avro    (one record per entity)
  <dir>/random-effect/<coord>/id-index.json      (entity string id <-> int)
Coefficients are stored as (name, term, value) triples remapped through the
feature IndexMap per shard, so models survive re-indexing — the same contract
the reference maintains (feature-index remapping, save:77-141 / load:143-265).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Optional, Tuple

import numpy as np

from photon_ml_tpu.data import avro as avro_io
from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.data.schemas import BAYESIAN_LINEAR_MODEL
from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.models.glm import Coefficients
from photon_ml_tpu.types import TaskType

FORMAT_VERSION = 1


class ModelLoadError(RuntimeError):
    """A model directory is missing or structurally broken (no metadata.json,
    no ``<shard>.idx``/``.phidx`` index maps, no ``<tag>.entities.json``
    entity indexes, or a coordinate referencing an absent shard map).

    One typed error so callers that must fail CLEANLY — above all the
    serving hot-swap path (serving/swap.py), which keeps the old model
    serving when the new directory is corrupt — can catch model-loading
    problems without fishing for raw ``KeyError``/``FileNotFoundError``."""

# JVM class the reference's loader instantiates via Class.forName(modelClass)
# (AvroUtils.scala:382-413).  Exported models MUST carry one of these names or
# Spark-side Photon ML throws IllegalArgumentException on load.
REFERENCE_MODEL_CLASS = {
    TaskType.LOGISTIC_REGRESSION:
        "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    TaskType.LINEAR_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    TaskType.POISSON_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        "com.linkedin.photon.ml.supervised.classification.SmoothedHingeLossLinearSVMModel",
}


def _coeff_to_record(model_id: str, means: np.ndarray, variances: Optional[np.ndarray],
                     index_map: IndexMap, loss_name: str,
                     model_class: str = "photon_ml_tpu.GLMModel") -> dict:
    triples = []
    var_triples = []
    for j in range(len(means)):
        v = float(means[j])
        if v == 0.0:
            continue  # sparse storage, like the reference's NTV lists
        name, term = index_map.get_feature_name(j)
        triples.append({"name": name, "term": term, "value": v})
        if variances is not None:
            var_triples.append({"name": name, "term": term, "value": float(variances[j])})
    return {
        "modelId": model_id,
        "modelClass": model_class,
        "means": triples,
        "variances": var_triples if variances is not None else None,
        "lossFunction": loss_name,
    }


def _record_to_coeff(rec: dict, index_map: IndexMap) -> Coefficients:
    means = np.zeros(index_map.size, np.float64)
    for ntv in rec["means"]:
        j = index_map.get_index(ntv["name"], ntv.get("term") or "")
        if j >= 0:
            means[j] = ntv["value"]
    variances = None
    if rec.get("variances"):
        variances = np.zeros(index_map.size, np.float64)
        for ntv in rec["variances"]:
            j = index_map.get_index(ntv["name"], ntv.get("term") or "")
            if j >= 0:
                variances[j] = ntv["value"]
    return Coefficients(means=means, variances=variances)


def _re_entity_rows(m: "RandomEffectModel", eidx: Optional[EntityIndex]):
    """(model_id, means, variances) per entity, sorted by entity id — the
    ONE definition of per-entity record identity/order, shared by the
    generic and native writers (their outputs must stay byte-semantics
    identical)."""
    for eid, slot in sorted(m.slot_of.items()):
        name = eidx.name_of(eid) if eidx is not None else None
        var = m.variances[slot] if m.variances is not None else None
        yield (name if name is not None else str(eid), m.w_stack[slot], var)


def _re_records(m: "RandomEffectModel", eidx: Optional[EntityIndex],
                imap: IndexMap, loss_name: str,
                model_class: str = "photon_ml_tpu.GLMModel"):
    """Per-entity BayesianLinearModelAvro records (generic-codec form)."""
    for model_id, means, var in _re_entity_rows(m, eidx):
        yield _coeff_to_record(model_id, means, var, imap, loss_name,
                               model_class=model_class)


def _index_map_fingerprint(imap) -> dict:
    """FULL-content fingerprint of an index map: {"scheme": ..., "value": ...}.

    Columnar models are POSITION-bound to their index maps; a same-size map
    with different contents would silently misassign every coefficient, so
    the loader verifies this fingerprint — complete coverage, not a sample.
    Two schemes (tagged, so loaders only compare like with like, and future
    scheme changes degrade to skipping the check rather than refusing valid
    models):

    - store maps hash their mmap file bytes (C speed, ~0.4s per GB);
    - dict maps hash every (key, id) pair in ITERATION order (deterministic
      for maps built by the same code path; a logically-equal map built in a
      different order refuses — the safe direction).

    Cached on the instance: save+load in one process pays the pass once.
    """
    import hashlib
    import itertools

    cached = getattr(imap, "_content_fp", None)
    if cached is not None:
        return cached
    from photon_ml_tpu.data.native_index import StoreIndexMap

    h = hashlib.sha1()
    if isinstance(imap, StoreIndexMap):
        scheme = "phfp1-store"
        with open(imap._path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 22), b""):
                h.update(chunk)
    else:
        scheme = "phfp1-items"
        h.update(f"{imap.size}:{imap.intercept_index}".encode())
        pairs = (f"{k}={i}" for k, i in imap.items())
        while True:
            block = "\x1f".join(itertools.islice(pairs, 65536))
            if not block:
                break
            h.update(block.encode())
    fp = {"scheme": scheme, "value": h.hexdigest()[:16]}
    try:
        imap._content_fp = fp
    except AttributeError:
        pass  # slotted/foreign map types just recompute
    return fp


def _write_fixed_avro(path: str, model_id: str, means, variances,
                      imap: IndexMap, loss_name: str,
                      model_class: str = "photon_ml_tpu.GLMModel") -> None:
    """ONE home for fixed-effect NTV writes: the native codec fast path
    (native/model_codec.cpp — index-ordered key blob + f64 arrays in, one
    avro record body out, O(1) python in d) with the generic pure-python
    codec as fallback.  Identical wire format either way."""
    from photon_ml_tpu.storage import native_model_codec as nmc

    if nmc.available() and hasattr(imap, "key_blob"):
        blob, off = imap.key_blob()
        if len(off) - 1 == len(means):
            body = nmc.encode_record(
                model_id, model_class, loss_name, blob, off,
                np.asarray(means, np.float64),
                None if variances is None
                else np.asarray(variances, np.float64))
            if body is not None:
                avro_io.write_container_raw(path, BAYESIAN_LINEAR_MODEL, [body])
                return
    rec = _coeff_to_record(model_id, means, variances, imap, loss_name,
                           model_class=model_class)
    avro_io.write_container(path, BAYESIAN_LINEAR_MODEL, [rec])


def _read_fixed_avro_fast(path: str, imap: IndexMap) -> Optional[Coefficients]:
    """Native-codec read half: only for single-record files whose writer
    schema is EXACTLY ours (the dispatch guard); None -> generic path."""
    from photon_ml_tpu.storage import native_model_codec as nmc

    if not nmc.available():
        return None
    try:
        schema, blocks = avro_io.read_container_raw(path)
    except (OSError, ValueError):
        return None
    if schema != BAYESIAN_LINEAR_MODEL:
        return None
    count, block = next(iter(blocks), (0, b""))
    if count != 1:
        return None
    dec = nmc.decode_record(block)
    if dec is None:
        return None
    means = np.zeros(imap.size, np.float64)
    idx = nmc.lookup_blob(imap, dec["means_keys"], dec["means_off"])
    ok = idx >= 0
    means[idx[ok]] = dec["means_vals"][ok]
    variances = None
    # an EMPTY variances array reads as None, exactly like the generic
    # path's falsy rec.get("variances") — loaders must agree
    if dec["vars_vals"] is not None and len(dec["vars_vals"]):
        variances = np.zeros(imap.size, np.float64)
        vi = nmc.lookup_blob(imap, dec["vars_keys"], dec["vars_off"])
        ok = vi >= 0
        variances[vi[ok]] = dec["vars_vals"][ok]
    return Coefficients(means=means, variances=variances)


def _write_re_avro_fast(path: str, m: "RandomEffectModel",
                        eidx: Optional[EntityIndex], imap: IndexMap,
                        loss_name: str,
                        model_class: str = "photon_ml_tpu.GLMModel") -> bool:
    """Per-entity NTV writes through the native codec — the entity-COUNT
    scale path (the reference's production random effects hold millions of
    per-member models).  Same record semantics as _re_records."""
    from photon_ml_tpu.storage import native_model_codec as nmc

    if not nmc.available() or not hasattr(imap, "key_blob"):
        return False
    blob, off = imap.key_blob()
    if len(off) - 1 != m.w_stack.shape[1]:
        return False

    def bodies():
        for model_id, means, var in _re_entity_rows(m, eidx):
            body = nmc.encode_record(
                model_id, model_class, loss_name, blob, off,
                np.asarray(means, np.float64),
                None if var is None else np.asarray(var, np.float64))
            if body is None:
                raise RuntimeError("native encode failed mid-stream")
            yield body

    avro_io.write_container_raw(path, BAYESIAN_LINEAR_MODEL, bodies())
    return True


def _read_re_avro_fast(cdir: str, imap: IndexMap,
                       eidx: Optional[EntityIndex]):
    """Native read of a random-effect coordinate directory; returns
    (w_stack, slot_of, variances) or None for the generic path.  Walks
    records inside each block via the decoder's consumed counts."""
    from photon_ml_tpu.storage import native_model_codec as nmc

    if not nmc.available():
        return None
    decoded = []  # (n_records, block-decode dict)
    for p in avro_io.list_avro_files(cdir):
        try:
            schema, blocks = avro_io.read_container_raw(p)
        except (OSError, ValueError):
            return None
        if schema != BAYESIAN_LINEAR_MODEL:
            return None
        for count, block in blocks:
            dec = nmc.decode_block(block, count)
            if dec is None:
                return None
            decoded.append((count, dec))
    n = sum(c for c, _ in decoded)
    if n == 0:
        return None
    w = np.zeros((n, imap.size), np.float64)
    any_var = any(len(d["vars_vals"]) for _, d in decoded)
    variances = np.zeros_like(w) if any_var else None
    slot_of: Dict[int, int] = {}
    base = 0
    for count, dec in decoded:
        # ONE batch key lookup for the whole block, then a vectorized
        # scatter: row ids from the per-record span lengths
        idx = nmc.lookup_blob(imap, dec["means_keys"], dec["means_key_off"])
        rows = base + np.repeat(np.arange(count), np.diff(dec["means_rec_off"]))
        ok = idx >= 0
        w[rows[ok], idx[ok]] = dec["means_vals"][ok]
        if variances is not None and len(dec["vars_vals"]):
            vi = nmc.lookup_blob(imap, dec["vars_keys"], dec["vars_key_off"])
            vrows = base + np.repeat(np.arange(count),
                                     np.diff(dec["vars_rec_off"]))
            vok = vi >= 0
            variances[vrows[vok], vi[vok]] = dec["vars_vals"][vok]
        ids_raw = dec["ids"].tobytes()
        io_ = dec["id_off"]
        for r in range(count):
            mid = ids_raw[io_[r]:io_[r + 1]].decode("utf-8")
            eid = eidx.get_or_add(mid) if eidx is not None else int(mid)
            slot_of[eid] = base + r
        base += count
    return w, slot_of, variances


def coordinate_rel_dir(cid: str, m) -> str:
    """Relative directory of one coordinate inside a model dir."""
    kind = "fixed-effect" if isinstance(m, FixedEffectModel) else "random-effect"
    return os.path.join(kind, cid)


def save_coordinate(
    cid: str,
    m,
    out_dir: str,
    index_maps: Dict[str, IndexMap],
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    fmt: str = "avro",
) -> dict:
    """Serialize ONE coordinate's model files; returns its metadata entry.

    Split out so incremental checkpoints (storage/checkpoint.py) can rewrite
    only the coordinate that changed and link the rest.

    ``fmt="avro"``: name-keyed NTV triples — index-map-independent and
    reference-portable, O(d) Python per coordinate.  ``fmt="columnar"``: raw
    coefficient arrays (npz) BOUND to this run's index maps — O(1) Python,
    seconds instead of minutes at 1e7+ features; the loader validates the
    binding (array length vs index-map size) and remaps entity ids by NAME
    through id-index.json, so warm starts stay correct across runs."""
    if fmt not in ("avro", "columnar"):
        raise ValueError(f"unknown model format {fmt!r} (avro|columnar)")
    entity_indexes = entity_indexes or {}
    cdir = os.path.join(out_dir, coordinate_rel_dir(cid, m))
    os.makedirs(cdir, exist_ok=True)
    fp = (_index_map_fingerprint(index_maps[m.feature_shard])
          if fmt == "columnar" and m.feature_shard in index_maps else None)
    if isinstance(m, FixedEffectModel):
        if fmt == "columnar":
            arrays = {"means": np.asarray(m.coefficients.means)}
            if m.coefficients.variances is not None:
                arrays["variances"] = np.asarray(m.coefficients.variances)
            np.savez(os.path.join(cdir, "coefficients.npz"), **arrays)
        else:
            _write_fixed_avro(os.path.join(cdir, "coefficients.avro"), cid,
                              m.coefficients.means, m.coefficients.variances,
                              index_maps[m.feature_shard], m.task.value)
        out = {"type": "fixed", "feature_shard": m.feature_shard}
        if fp is not None:
            out["index_fingerprint"] = fp
        return out
    def _write_entity_directory(cdir, m, eidx):
        """(entity_ids, slots) arrays + the id-index.json name remap — the
        ONE id-map contract both random-effect containers' columnar saves
        share (the loader resolves names through it either way)."""
        eids = np.asarray(sorted(m.slot_of), np.int64)
        slots = np.asarray([m.slot_of[int(e)] for e in eids], np.int64)
        id_map = {str(eid): (eidx.name_of(eid) if eidx is not None
                             else str(eid))
                  for eid in m.slot_of}
        with open(os.path.join(cdir, "id-index.json"), "w") as f:
            json.dump(id_map, f)
        return eids, slots

    from photon_ml_tpu.models.game import CompactRandomEffectModel

    if isinstance(m, CompactRandomEffectModel):
        # the wide-vocabulary container saves NATIVELY sparse in the
        # columnar format (its whole point is never materializing [E, d]);
        # the reference-format avro writers walk dense rows, so that export
        # asks for an explicit to_dense()
        if fmt != "columnar":
            raise ValueError(
                f"coordinate {cid!r}: CompactRandomEffectModel saves in the "
                "columnar format only — pass fmt='columnar', or convert "
                "with .to_dense() for the reference avro format")
        eidx = entity_indexes.get(m.random_effect_type)
        eids, slots = _write_entity_directory(cdir, m, eidx)
        np.savez(os.path.join(cdir, "coefficients.npz"),
                 re_indices=np.asarray(m.indices),
                 re_values=np.asarray(m.values),
                 re_dim=np.asarray(m.dim, np.int64),
                 entity_ids=eids, slots=slots)
        out = {"type": "random", "feature_shard": m.feature_shard,
               "random_effect_type": m.random_effect_type}
        if fp is not None:
            out["index_fingerprint"] = fp
        return out
    if isinstance(m, RandomEffectModel):
        eidx = entity_indexes.get(m.random_effect_type)
        eids, slots = _write_entity_directory(cdir, m, eidx)
        if fmt == "columnar":
            arrays = {"w_stack": np.asarray(m.w_stack), "entity_ids": eids,
                      "slots": slots}
            if m.variances is not None:
                arrays["variances"] = np.asarray(m.variances)
            np.savez(os.path.join(cdir, "coefficients.npz"), **arrays)
        if fmt != "columnar":
            # single-process save = the multihost writer's part=0 case: ONE
            # definition of the record write (save_random_effect_part)
            out = save_random_effect_part(cid, m, out_dir,
                                          index_maps[m.feature_shard], eidx,
                                          part=0)
        else:
            out = {
                "type": "random",
                "feature_shard": m.feature_shard,
                "random_effect_type": m.random_effect_type,
            }
        if fp is not None:
            out["index_fingerprint"] = fp
        return out
    raise TypeError(f"cannot save model type {type(m)!r}")


def save_random_effect_part(coordinate_id: str, model, out_dir: str,
                            index_map, entity_index=None,
                            part: int = 0) -> dict:
    """Write ONE host's random-effect entities as a part file into the
    shared model directory (reference: executors write part-NNNNN avro
    files per partition; the loader here already merges the whole
    directory — ``avro_io.read_directory`` in ``load_game_model``).
    Used by the multihost train driver: every process calls this with its
    own entities and ``part=process_index``; returns the coordinate's
    metadata dict (identical on every host)."""
    cdir = os.path.join(out_dir, "random-effect", coordinate_id)
    os.makedirs(cdir, exist_ok=True)
    rpath = os.path.join(cdir, f"part-{part:05d}.avro")
    if not _write_re_avro_fast(rpath, model, entity_index, index_map,
                               model.task.value):
        avro_io.write_container(
            rpath, BAYESIAN_LINEAR_MODEL,
            _re_records(model, entity_index, index_map, model.task.value))
    return {"type": "random", "feature_shard": model.feature_shard,
            "random_effect_type": model.random_effect_type}


def save_game_model(
    model: GameModel,
    out_dir: str,
    index_maps: Dict[str, IndexMap],
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    task: TaskType = TaskType.LOGISTIC_REGRESSION,
    fmt: str = "avro",
) -> None:
    """``fmt="avro"`` (default): name-keyed NTV triples — index-map-
    independent and reference-portable, but O(d) Python work per coordinate.
    ``fmt="columnar"``: raw coefficient arrays (npz) BOUND to the saving
    run's index maps — O(1) Python work, seconds instead of minutes at 1e7+
    features; the fast path for checkpoint/warm-start loops where the index
    maps are saved right alongside (the train driver always writes them)."""
    os.makedirs(out_dir, exist_ok=True)
    meta = {"version": FORMAT_VERSION, "task": task.value, "coordinates": {}}
    if fmt == "columnar":
        meta["format"] = "columnar"
    for cid, m in model.models.items():
        meta["coordinates"][cid] = save_coordinate(cid, m, out_dir, index_maps,
                                                   entity_indexes, fmt=fmt)
    with open(os.path.join(out_dir, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=2)


def load_game_model(
    model_dir: str,
    index_maps: Dict[str, IndexMap],
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
) -> Tuple[GameModel, TaskType]:
    meta_path = os.path.join(model_dir, "metadata.json")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except FileNotFoundError:
        raise ModelLoadError(
            f"{model_dir!r} is not a model directory: missing metadata.json "
            "(expected a dir written by save_game_model, e.g. <output>/best)")
    except ValueError as e:  # json.JSONDecodeError
        raise ModelLoadError(f"{meta_path!r} is corrupt: {e}")
    try:
        task = TaskType(meta["task"])
    except (KeyError, ValueError) as e:
        raise ModelLoadError(f"{meta_path!r} has no valid task entry: {e}")
    entity_indexes = entity_indexes or {}
    models: Dict[str, object] = {}

    if meta.get("format") == "columnar":
        def _check_binding(cid, info, d_saved):
            # columnar coefficients are POSITION-bound to the saving run's
            # index map — a size mismatch OR content churn (same size,
            # shuffled positions: checked via the saved fingerprint) means
            # the features moved; fail loudly instead of silently
            # misassigning every coefficient
            imap = index_maps.get(info["feature_shard"])
            if imap is None:
                return
            bound = (f"columnar models bind to the saving run's index maps "
                     f"(load with those maps, or re-save as the portable "
                     f"avro format)")
            if d_saved != imap.size:
                raise ValueError(
                    f"columnar model coordinate {cid!r} has {d_saved} "
                    f"coefficients but index map for shard "
                    f"{info['feature_shard']!r} has {imap.size} features — "
                    + bound)
            saved_fp = info.get("index_fingerprint")
            if isinstance(saved_fp, dict):
                ours = _index_map_fingerprint(imap)
                # compare only like schemes: an unknown/different scheme
                # (older model, different map kind) skips the check instead
                # of refusing a valid model
                if (saved_fp.get("scheme") == ours["scheme"]
                        and saved_fp.get("value") != ours["value"]):
                    raise ValueError(
                        f"columnar model coordinate {cid!r}: index map for "
                        f"shard {info['feature_shard']!r} has the same size "
                        f"but different contents than the saving run's — "
                        + bound)

        for cid, info in meta["coordinates"].items():
            shard = info["feature_shard"]
            if info["type"] == "fixed":
                z = np.load(os.path.join(model_dir, "fixed-effect", cid,
                                         "coefficients.npz"))
                _check_binding(cid, info, z["means"].shape[-1])
                models[cid] = FixedEffectModel(
                    coefficients=Coefficients(
                        means=z["means"],
                        variances=z["variances"] if "variances" in z else None),
                    feature_shard=shard, task=task)
            else:
                cdir = os.path.join(model_dir, "random-effect", cid)
                z = np.load(os.path.join(cdir, "coefficients.npz"))
                compact = "re_indices" in z
                _check_binding(cid, info,
                               int(z["re_dim"]) if compact
                               else z["w_stack"].shape[-1])
                re_type = info["random_effect_type"]
                # entity ids remap BY NAME through id-index.json (same
                # contract as the avro path's _stack_random_effect): the
                # loading run's EntityIndex may number entities differently
                eidx = entity_indexes.get(re_type)
                with open(os.path.join(cdir, "id-index.json")) as f:
                    name_of = json.load(f)
                slot_of = {}
                for e, s in zip(z["entity_ids"], z["slots"]):
                    name = name_of.get(str(int(e)))
                    eid = (eidx.get_or_add(name)
                           if eidx is not None and name is not None
                           else int(e))
                    slot_of[eid] = int(s)
                if compact:
                    from photon_ml_tpu.models.game import \
                        CompactRandomEffectModel

                    models[cid] = CompactRandomEffectModel(
                        indices=z["re_indices"], values=z["re_values"],
                        dim=int(z["re_dim"]), slot_of=slot_of,
                        random_effect_type=re_type,
                        feature_shard=shard, task=task)
                else:
                    models[cid] = RandomEffectModel(
                        w_stack=z["w_stack"], slot_of=slot_of,
                        random_effect_type=re_type,
                        feature_shard=shard, task=task,
                        variances=z["variances"] if "variances" in z
                        else None)
        return GameModel(models=models), task

    for cid, info in meta["coordinates"].items():
        shard = info["feature_shard"]
        imap = index_maps.get(shard)
        if imap is None:
            raise ModelLoadError(
                f"coordinate {cid!r} needs the index map for feature shard "
                f"{shard!r} — the model directory (or its parent) is missing "
                f"{shard}.idx/{shard}.phidx")
        if info["type"] == "fixed":
            path = os.path.join(model_dir, "fixed-effect", cid, "coefficients.avro")
            coeff = _read_fixed_avro_fast(path, imap)
            if coeff is None:
                rec = next(iter(avro_io.read_container(path)))
                coeff = _record_to_coeff(rec, imap)
            models[cid] = FixedEffectModel(
                coefficients=coeff, feature_shard=shard, task=task)
        else:
            cdir = os.path.join(model_dir, "random-effect", cid)
            re_type = info["random_effect_type"]
            fast = _read_re_avro_fast(cdir, imap, entity_indexes.get(re_type))
            if fast is not None:
                w, slot_of, variances = fast
            else:
                recs = list(avro_io.read_directory(cdir))
                w, slot_of, variances = _stack_random_effect(
                    recs, imap, entity_indexes.get(re_type))
            models[cid] = RandomEffectModel(
                w_stack=w, slot_of=slot_of, random_effect_type=re_type,
                feature_shard=shard, task=task, variances=variances)
    return GameModel(models=models), task


@dataclasses.dataclass
class ModelBundle:
    """Everything needed to score with a trained model: the model itself,
    its task, and the feature/entity indexes saved alongside it."""

    model: GameModel
    task: TaskType
    index_maps: Dict[str, IndexMap]
    entity_indexes: Dict[str, EntityIndex]
    model_dir: str  # the resolved dir holding metadata.json


def load_model_bundle(model_dir: str) -> ModelBundle:
    """Load a training-output directory as one scoring-ready bundle.

    Accepts either the training output dir (``<dir>/best/metadata.json`` +
    ``<dir>/<shard>.idx`` + ``<dir>/<tag>.entities.json``) or a model dir
    itself (``<dir>/metadata.json``, artifacts alongside).  This is the ONE
    resolution path shared by the batch scorer (cli/score.py), the online
    scorer (cli/serve.py), and hot model swap (serving/swap.py) — every
    failure mode raises :class:`ModelLoadError` with an actionable message,
    never a raw ``KeyError``/``FileNotFoundError``, because the swap path
    must distinguish "new model dir is broken, keep serving the old one"
    from a programming error.
    """
    if not os.path.isdir(model_dir):
        raise ModelLoadError(f"model dir {model_dir!r} does not exist")
    sub = os.path.join(model_dir, "best")
    if os.path.exists(os.path.join(model_dir, "metadata.json")):
        mdir = model_dir
        # artifacts may sit beside metadata.json (direct dir) or one level
        # up (the training layout's <out>/best); scan both, best-dir first
        scan_dirs = [model_dir, os.path.dirname(os.path.abspath(model_dir))]
    elif os.path.exists(os.path.join(sub, "metadata.json")):
        mdir = sub
        scan_dirs = [sub, model_dir]
    else:
        raise ModelLoadError(
            f"{model_dir!r} holds no model: neither metadata.json nor "
            "best/metadata.json exists (expected a training --output-dir or "
            "a save_game_model directory)")

    from photon_ml_tpu.data.index_map import load_index

    index_maps: Dict[str, IndexMap] = {}
    entity_indexes: Dict[str, EntityIndex] = {}
    for d in scan_dirs:
        try:
            names = os.listdir(d)
        except OSError:
            continue
        for name in sorted(names):
            path = os.path.join(d, name)
            if name.endswith((".idx", ".phidx")):
                shard = name.rsplit(".", 1)[0]
                if shard not in index_maps:
                    try:
                        index_maps[shard] = load_index(path)
                    except (OSError, ValueError) as e:
                        raise ModelLoadError(
                            f"index map {path!r} is unreadable: {e}")
            elif name.endswith(".entities.json"):
                tag = name[: -len(".entities.json")]
                if tag not in entity_indexes:
                    try:
                        entity_indexes[tag] = EntityIndex.load(path)
                    except (OSError, ValueError) as e:
                        raise ModelLoadError(
                            f"entity index {path!r} is unreadable: {e}")

    # Pre-flight BEFORE decoding coefficients: a random-effect coordinate
    # without its <tag>.entities.json would otherwise fail deep inside the
    # loader (or worse, load with unresolvable entity names)
    meta_path = os.path.join(mdir, "metadata.json")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        raise ModelLoadError(f"{meta_path!r} is unreadable: {e}")
    for cid, info in (meta.get("coordinates") or {}).items():
        re_type = info.get("random_effect_type")
        if info.get("type") == "random" and re_type not in (None, ""):
            if re_type not in entity_indexes:
                raise ModelLoadError(
                    f"coordinate {cid!r} is a random effect over {re_type!r} "
                    f"but {re_type}.entities.json was not found next to the "
                    f"model (searched {scan_dirs}) — entity names cannot be "
                    "resolved")

    model, task = load_game_model(mdir, index_maps, entity_indexes)
    return ModelBundle(model=model, task=task, index_maps=index_maps,
                       entity_indexes=entity_indexes, model_dir=mdir)


def save_glm_text(model: FixedEffectModel, index_map: IndexMap, path: str) -> None:
    """Human-readable text model (reference GLMSuite.writeModelsToText)."""
    with open(path, "w") as f:
        means = model.coefficients.means
        for j in np.argsort(-np.abs(means)):
            if means[j] == 0.0:
                continue
            name, term = index_map.get_feature_name(int(j))
            f.write(f"{name}\t{term}\t{means[j]:.17g}\n")


def _stack_random_effect(recs, imap: IndexMap,
                         eidx: Optional[EntityIndex]):
    """records -> (w_stack, slot_of, variances); shared by the native loader
    and the reference-format importer."""
    w = np.zeros((len(recs), imap.size), np.float64)
    any_var = any(r.get("variances") for r in recs)
    variances = np.zeros((len(recs), imap.size), np.float64) if any_var else None
    slot_of: Dict[int, int] = {}
    for slot, rec in enumerate(recs):
        c = _record_to_coeff(rec, imap)
        w[slot] = c.means
        if variances is not None and c.variances is not None:
            variances[slot] = c.variances
        if eidx is not None:
            eid = eidx.get_or_add(str(rec["modelId"]))
        else:
            eid = int(rec["modelId"])
        slot_of[eid] = slot
    return w, slot_of, variances


def import_reference_game_model(
    model_dir: str,
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    index_maps: Optional[Dict[str, IndexMap]] = None,
    shard_of: Optional[Dict[str, str]] = None,
    only: Optional[set] = None,
) -> Tuple[GameModel, TaskType, Dict[str, IndexMap], Dict[str, EntityIndex]]:
    """Import a GAME model saved by LinkedIn Photon ML ITSELF — the migration
    path for existing users (reference on-disk layout,
    ModelProcessingUtils.scala:77-141 save / 489-607 metadata):

        <dir>/model-metadata.json
        <dir>/fixed-effect/<coord>/id-info              ([featureShardId])
        <dir>/fixed-effect/<coord>/coefficients/part-*.avro
        <dir>/random-effect/<coord>/id-info             ([randomEffectType,
                                                          featureShardId])
        <dir>/random-effect/<coord>/**.avro             (one record/entity)

    The authoritative randomEffectType / featureShardId come from each
    coordinate's ``id-info`` file, exactly where the reference's own loader
    reads them (ModelProcessingUtils.scala:99-101, 116-121); the directory
    name is only the coordinate's name.  Feature index maps are REBUILT from
    the stored (name, term) triples, keyed by featureShardId and UNIONED
    across coordinates sharing a shard — the reference's models are
    index-map-independent by design (coefficients stored by feature name), so
    no PalDB store is needed to import.  Returns (model, task, index_maps
    keyed by featureShardId, entity_indexes).

    ``index_maps``/``shard_of``: remap the stored coefficients into EXISTING
    feature index maps instead of rebuilding them — the warm-start path,
    where the imported model must align with the training data's indexing.
    ``shard_of`` overrides a coordinate's shard name (imported coordinate id
    -> this run's feature-shard name).  ``only`` restricts the import to the
    named coordinates (subset migration: other coordinate directories are
    skipped entirely, never decoded).
    """
    import glob as _glob

    from photon_ml_tpu.data.index_map import feature_key

    with open(os.path.join(model_dir, "model-metadata.json")) as f:
        meta = json.load(f)
    task = TaskType[meta["modelType"]]
    entity_indexes = dict(entity_indexes or {})
    provided_maps = index_maps
    shard_of = shard_of or {}

    def _records_under(cdir: str):
        paths = sorted(_glob.glob(os.path.join(cdir, "**", "*.avro"),
                                  recursive=True))
        for p in paths:
            yield from avro_io.read_container(p)

    def _id_info(cdir: str):
        path = os.path.join(cdir, "id-info")
        if not os.path.exists(path):
            return []
        with open(path) as f:
            return [line.strip() for line in f if line.strip()]

    # Pass 1: scan coordinate directories, STREAMING records (only feature
    # keys are collected — production reference models hold millions of
    # per-entity records, which must never all live in memory at once)
    scanned = []  # (kind, cid, cdir, re_type, shard)
    skipped = []  # coordinate dirs excluded by ``only`` (for error messages)
    per_shard: Dict[str, Dict[str, None]] = {}
    for kind in ("fixed-effect", "random-effect"):
        root = os.path.join(model_dir, kind)
        if not os.path.isdir(root):
            continue
        for cid in sorted(os.listdir(root)):
            cdir = os.path.join(root, cid)
            if not os.path.isdir(cdir):
                continue
            if only is not None and cid not in only:
                skipped.append(cid)
                continue
            info = _id_info(cdir)
            if kind == "fixed-effect":
                re_type = None
                shard = info[0] if info else cid
            else:
                # dir-name '<type>-<shard>' fallback for hand-built layouts
                re_type = info[0] if info else cid.split("-")[0]
                shard = info[1] if len(info) > 1 else cid
            shard = shard_of.get(cid, shard)
            if provided_maps is not None:
                # maps supplied: only emptiness matters — decode ONE record
                empty = next(iter(_records_under(cdir)), None) is None
            else:
                empty = True
                keys = per_shard.setdefault(shard, {})
                for rec in _records_under(cdir):
                    empty = False
                    for ntv in rec["means"]:
                        keys.setdefault(feature_key(ntv["name"],
                                                    ntv.get("term") or ""),
                                        None)
            if not empty:
                scanned.append((kind, cid, cdir, re_type, shard))

    if not scanned:
        if only is not None and skipped:
            raise FileNotFoundError(
                f"none of the requested coordinates {sorted(only)} exist "
                f"under {model_dir!r}; the model contains {sorted(skipped)}")
        raise FileNotFoundError(
            f"no coordinate models found under {model_dir!r} "
            "(expected fixed-effect/ and/or random-effect/ subdirectories)")

    # Index maps per featureShardId — UNION of every sharing coordinate's
    # features (one map per shard, like the reference)
    if provided_maps is not None:
        index_maps = dict(provided_maps)
        for _, cid, _, _, shard in scanned:
            if shard not in index_maps:
                raise KeyError(
                    f"imported coordinate {cid!r} needs index map for shard "
                    f"{shard!r}; provide it (or a shard_of entry)")
    else:
        index_maps = {shard: IndexMap({k: i for i, k in enumerate(sorted(keys))})
                      for shard, keys in per_shard.items()}

    # Pass 2: models, re-streaming each coordinate's files one at a time
    models: Dict[str, object] = {}
    for kind, cid, cdir, re_type, shard in scanned:
        imap = index_maps[shard]
        if kind == "fixed-effect":
            rec = next(iter(_records_under(cdir)))
            models[cid] = FixedEffectModel(
                coefficients=_record_to_coeff(rec, imap),
                feature_shard=shard, task=task)
        else:
            eidx = entity_indexes.setdefault(re_type, EntityIndex())
            recs = list(_records_under(cdir))
            w, slot_of_, variances = _stack_random_effect(recs, imap, eidx)
            models[cid] = RandomEffectModel(
                w_stack=w, slot_of=slot_of_, random_effect_type=re_type,
                feature_shard=shard, task=task, variances=variances)

    return GameModel(models=models), task, index_maps, entity_indexes


def export_reference_game_model(
    model: GameModel,
    out_dir: str,
    index_maps: Dict[str, IndexMap],
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    task: TaskType = TaskType.LOGISTIC_REGRESSION,
) -> None:
    """Write a GAME model in the REFERENCE'S on-disk layout so Spark-side
    Photon ML consumers can load it (the inverse of
    ``import_reference_game_model``; ModelProcessingUtils.scala:77-141):

        <dir>/model-metadata.json                       ({"modelType": ...})
        <dir>/fixed-effect/<coord>/id-info              ([featureShardId])
        <dir>/fixed-effect/<coord>/coefficients/part-00000.avro
        <dir>/random-effect/<coord>/id-info             ([type, shardId])
        <dir>/random-effect/<coord>/coefficients/part-00000.avro

    Records carry the reference's own JVM modelClass names (loaded via
    Class.forName, AvroUtils.scala:382-413) and random-effect records live
    under coefficients/ exactly where the reference's loader globs them
    (ModelProcessingUtils.scala:229 AvroConstants.COEFFICIENTS,
    saveRandomEffectModelToHDFS:278).
    """
    entity_indexes = entity_indexes or {}
    jvm_class = REFERENCE_MODEL_CLASS[task]
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "model-metadata.json"), "w") as f:
        json.dump({"modelType": task.name}, f, indent=2)

    for cid, m in model.models.items():
        imap = index_maps[m.feature_shard]
        if isinstance(m, FixedEffectModel):
            cdir = os.path.join(out_dir, "fixed-effect", cid)
            os.makedirs(os.path.join(cdir, "coefficients"), exist_ok=True)
            with open(os.path.join(cdir, "id-info"), "w") as f:
                f.write(m.feature_shard + "\n")
            _write_fixed_avro(
                os.path.join(cdir, "coefficients", "part-00000.avro"), cid,
                m.coefficients.means, m.coefficients.variances, imap,
                task.value, model_class=jvm_class)
        elif isinstance(m, RandomEffectModel):
            cdir = os.path.join(out_dir, "random-effect", cid)
            os.makedirs(os.path.join(cdir, "coefficients"), exist_ok=True)
            with open(os.path.join(cdir, "id-info"), "w") as f:
                f.write(m.random_effect_type + "\n" + m.feature_shard + "\n")
            eidx = entity_indexes.get(m.random_effect_type)
            rpath = os.path.join(cdir, "coefficients", "part-00000.avro")
            if not _write_re_avro_fast(rpath, m, eidx, imap, task.value,
                                       model_class=jvm_class):
                avro_io.write_container(
                    rpath, BAYESIAN_LINEAR_MODEL,
                    _re_records(m, eidx, imap, task.value,
                                model_class=jvm_class))
        else:
            raise TypeError(f"cannot export model type {type(m)!r}")
