from photon_ml_tpu.storage.model_io import (  # noqa: F401
    ModelBundle,
    ModelLoadError,
    save_game_model,
    load_game_model,
    load_model_bundle,
    save_glm_text,
)
from photon_ml_tpu.storage.checkpoint import save_checkpoint, load_checkpoint  # noqa: F401
