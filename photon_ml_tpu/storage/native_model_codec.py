"""ctypes glue for native/model_codec.cpp — bulk BayesianLinearModelAvro
record bodies as flat buffers (packed key blob + offsets + f64 values).

The huge-d fixed-effect fast path for the PORTABLE model format: python-side
work is O(1) in d on both save and load (storage/model_io.py falls back to
the generic pure-python codec when the native library is unavailable or the
writer schema isn't ours).
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from photon_ml_tpu.native.build import compile_library

_lib = None
_tried = False


def _native():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    path = compile_library("model_codec")
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    i64, p = ctypes.c_int64, ctypes.c_void_p
    lib.plmc_encode.restype = i64
    lib.plmc_encode.argtypes = [p, i64, p, i64, p, i64, p, p, p, p, i64, p, i64]
    lib.plmc_scan.restype = i64
    lib.plmc_scan.argtypes = [p, i64] + [p] * 8
    lib.plmc_fill.restype = i64
    lib.plmc_fill.argtypes = [p, i64] + [p] * 9
    lib.plmc_scan_block.restype = i64
    lib.plmc_scan_block.argtypes = [p, i64, i64] + [p] * 5
    lib.plmc_fill_block.restype = i64
    lib.plmc_fill_block.argtypes = [p, i64, i64] + [p] * 10
    _lib = lib
    return _lib


def available() -> bool:
    return _native() is not None


def encode_record(model_id: str, model_class: Optional[str],
                  loss: Optional[str], keys_blob: np.ndarray,
                  key_offsets: np.ndarray, values: np.ndarray,
                  variances: Optional[np.ndarray]) -> Optional[bytes]:
    """One record body (avro binary) from index-ordered flat buffers;
    zero means are skipped (sparse NTV storage).  None when unavailable."""
    lib = _native()
    if lib is None:
        return None
    mid = model_id.encode()
    mcls = model_class.encode() if model_class is not None else b""
    lo = loss.encode() if loss is not None else b""
    values = np.ascontiguousarray(values, np.float64)
    var = (np.ascontiguousarray(variances, np.float64)
           if variances is not None else None)
    key_offsets = np.ascontiguousarray(key_offsets, np.int64)
    keys_blob = np.ascontiguousarray(keys_blob, np.uint8)
    d = len(values)
    cap = 256  # first call returns the needed size
    for _ in range(2):
        out = ctypes.create_string_buffer(cap)
        n = lib.plmc_encode(
            mid, len(mid), mcls, len(mcls) if model_class is not None else -1,
            lo, len(lo) if loss is not None else -1,
            keys_blob.ctypes.data, key_offsets.ctypes.data,
            values.ctypes.data,
            var.ctypes.data if var is not None else None,
            d, out, cap)
        if n > 0:
            return out.raw[:n]
        if n == 0:
            return None
        cap = -n
    return None


def decode_record(buf: bytes, offset: int = 0):
    """Decode ONE record body starting at ``offset``.

    Returns None when unavailable/malformed, else a dict:
      model_id/model_class/loss: str | None
      means_keys (uint8 blob), means_off (int64[n+1]), means_vals (f64[n])
      vars_keys/vars_off/vars_vals: same or None
      consumed: bytes read (for walking multi-record blocks)
    """
    lib = _native()
    if lib is None:
        return None
    if not isinstance(buf, bytes):
        buf = bytes(buf)
    # pointer into the bytes object's buffer (no copy); `buf` stays
    # referenced for the duration of both native calls below
    keep = ctypes.c_char_p(buf)
    ptr = ctypes.cast(keep, ctypes.c_void_p).value + offset
    blen = len(buf) - offset

    c = [ctypes.c_int64() for _ in range(8)]
    ok = lib.plmc_scan(ptr, blen, *[ctypes.byref(x) for x in c])
    if not ok:
        return None
    consumed, n_means, mk_bytes, n_vars, vk_bytes, id_len, cls_len, loss_len = (
        int(x.value) for x in c)

    mid = ctypes.create_string_buffer(max(id_len, 1))
    mcls = ctypes.create_string_buffer(max(cls_len, 1))
    lo = ctypes.create_string_buffer(max(loss_len, 1))
    mk = np.empty(max(mk_bytes, 1), np.uint8)
    moff = np.empty(n_means + 1, np.int64)
    mvals = np.empty(n_means, np.float64)
    has_vars = n_vars >= 0
    vk = np.empty(max(vk_bytes, 1), np.uint8)
    voff = np.empty((n_vars + 1) if has_vars else 1, np.int64)
    vvals = np.empty(max(n_vars, 0) if has_vars else 0, np.float64)

    got = lib.plmc_fill(
        ptr, blen, mid, mcls, lo,
        mk.ctypes.data, moff.ctypes.data, mvals.ctypes.data,
        vk.ctypes.data, voff.ctypes.data, vvals.ctypes.data)
    if got != consumed:
        return None
    return {
        "model_id": mid.raw[:id_len].decode(),
        "model_class": mcls.raw[:cls_len].decode() if cls_len >= 0 else None,
        "loss": lo.raw[:loss_len].decode() if loss_len >= 0 else None,
        "means_keys": mk[:mk_bytes], "means_off": moff, "means_vals": mvals,
        "vars_keys": vk[:vk_bytes] if has_vars else None,
        "vars_off": voff if has_vars else None,
        "vars_vals": vvals if has_vars else None,
        "consumed": consumed,
    }


def decode_block(block: bytes, n_records: int):
    """Decode ALL records of a container block in two native calls — the
    entity-count scale path (per-record boundary crossings dominate when
    records are small and numerous).

    Returns None on unavailability/malformed input, else a dict of
    concatenated arrays:
      ids (uint8 blob), id_off[n+1]
      means_keys/means_key_off[total+1]/means_vals[total],
      means_rec_off[n+1] (record boundaries into the means arrays)
      vars_*: same shape (absent arrays contribute 0-length spans)
    """
    lib = _native()
    if lib is None:
        return None
    if not isinstance(block, bytes):
        block = bytes(block)
    keep = ctypes.c_char_p(block)
    ptr = ctypes.cast(keep, ctypes.c_void_p).value
    c = [ctypes.c_int64() for _ in range(5)]
    ok = lib.plmc_scan_block(ptr, len(block), n_records,
                             *[ctypes.byref(x) for x in c])
    if not ok:
        return None
    t_means, mk_bytes, t_vars, vk_bytes, id_bytes = (int(x.value) for x in c)

    ids = np.empty(max(id_bytes, 1), np.uint8)
    id_off = np.empty(n_records + 1, np.int64)
    mkeys = np.empty(max(mk_bytes, 1), np.uint8)
    mkey_off = np.empty(t_means + 1, np.int64)
    mvals = np.empty(t_means, np.float64)
    mrec_off = np.empty(n_records + 1, np.int64)
    vkeys = np.empty(max(vk_bytes, 1), np.uint8)
    vkey_off = np.empty(t_vars + 1, np.int64)
    vvals = np.empty(max(t_vars, 0), np.float64)
    vrec_off = np.empty(n_records + 1, np.int64)

    got = lib.plmc_fill_block(
        ptr, len(block), n_records,
        ids.ctypes.data, id_off.ctypes.data,
        mkeys.ctypes.data, mkey_off.ctypes.data, mvals.ctypes.data,
        mrec_off.ctypes.data,
        vkeys.ctypes.data, vkey_off.ctypes.data, vvals.ctypes.data,
        vrec_off.ctypes.data)
    if got != ok:
        return None
    return {
        "ids": ids[:id_bytes], "id_off": id_off,
        "means_keys": mkeys[:mk_bytes], "means_key_off": mkey_off,
        "means_vals": mvals, "means_rec_off": mrec_off,
        "vars_keys": vkeys[:vk_bytes], "vars_key_off": vkey_off,
        "vars_vals": vvals, "vars_rec_off": vrec_off,
    }


def lookup_blob(imap, blob: np.ndarray, offsets: np.ndarray) -> np.ndarray:
    """Feature indices for a packed key blob against any index-map kind."""
    getter = getattr(imap, "get_indices_blob", None)
    if getter is not None:
        return getter(blob, offsets)
    raw = blob.tobytes()
    keys = [raw[offsets[i]:offsets[i + 1]].decode("utf-8")
            for i in range(len(offsets) - 1)]
    return imap.get_indices(keys)
