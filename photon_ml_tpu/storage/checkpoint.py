"""Coordinate-descent checkpoint / resume.

The reference has NO mid-job checkpointing (SURVEY.md §5: fault tolerance is
Spark lineage + persist).  This is an improvement the survey calls for
(§7 layer 7): after every coordinate update the descent state (models +
iteration cursor) can be flushed so a preempted TPU job resumes instead of
restarting — preemption being the TPU-world failure mode that Spark lineage
addressed on YARN.

Crash safety: versioned subdirectories + an atomically-replaced LATEST
pointer file.  A kill at ANY instant leaves either the previous or the new
checkpoint fully loadable; stale versions are pruned only after the pointer
moves.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
from typing import Dict, Optional, Tuple

from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.storage.model_io import load_game_model, save_game_model
from photon_ml_tpu.types import TaskType

_POINTER = "LATEST"


def _read_pointer(ckpt_dir: str) -> Optional[str]:
    try:
        with open(os.path.join(ckpt_dir, _POINTER)) as f:
            return f.read().strip()
    except FileNotFoundError:
        return None


def save_checkpoint(
    ckpt_dir: str,
    model: GameModel,
    index_maps: Dict[str, IndexMap],
    cursor: Dict[str, int],
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    task: TaskType = TaskType.LOGISTIC_REGRESSION,
) -> None:
    """``cursor``: {"iteration": i, "coordinate": k} — the NEXT update to run."""
    os.makedirs(ckpt_dir, exist_ok=True)
    # Version = max existing v<N> + 1, NOT pointer+1: a crash between the
    # version rename and the pointer swap leaves an orphaned v<N+1>, and
    # deriving from the pointer would collide with it forever after.
    existing = [int(name[1:]) for name in os.listdir(ckpt_dir)
                if re.fullmatch(r"v\d+", name)]
    version = f"v{max(existing, default=0) + 1}"

    tmp = tempfile.mkdtemp(prefix=".tmp-", dir=ckpt_dir)
    try:
        save_game_model(model, tmp, index_maps, entity_indexes, task)
        with open(os.path.join(tmp, "cursor.json"), "w") as f:
            json.dump(cursor, f)
        os.rename(tmp, os.path.join(ckpt_dir, version))  # atomic: new name
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise

    # atomic pointer swap, then prune every superseded/orphaned version
    ptr_tmp = os.path.join(ckpt_dir, f".{_POINTER}.tmp")
    with open(ptr_tmp, "w") as f:
        f.write(version)
        f.flush()
        os.fsync(f.fileno())
    os.replace(ptr_tmp, os.path.join(ckpt_dir, _POINTER))
    for name in os.listdir(ckpt_dir):
        stale = (re.fullmatch(r"v\d+", name) and name != version) or name.startswith(".tmp-")
        if stale:
            shutil.rmtree(os.path.join(ckpt_dir, name), ignore_errors=True)


def load_checkpoint(
    ckpt_dir: str,
    index_maps: Dict[str, IndexMap],
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
) -> Tuple[GameModel, TaskType, Dict[str, int]]:
    version = _read_pointer(ckpt_dir)
    if version is None:
        raise FileNotFoundError(f"no checkpoint pointer in {ckpt_dir}")
    vdir = os.path.join(ckpt_dir, version)
    model, task = load_game_model(vdir, index_maps, entity_indexes)
    with open(os.path.join(vdir, "cursor.json")) as f:
        cursor = json.load(f)
    return model, task, cursor
