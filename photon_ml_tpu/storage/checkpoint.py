"""Coordinate-descent checkpoint / resume.

The reference has NO mid-job checkpointing (SURVEY.md §5: fault tolerance is
Spark lineage + persist).  This is an improvement the survey calls for
(§7 layer 7): after every coordinate update the descent state (models +
iteration cursor + best-so-far model) can be flushed so a preempted TPU job
resumes instead of restarting — preemption being the TPU-world failure mode
that Spark lineage addressed on YARN.

Crash safety: versioned subdirectories + an atomically-replaced LATEST
pointer file.  A kill at ANY instant leaves either the previous or the new
checkpoint fully loadable; stale versions are pruned only after the pointer
moves.

Incremental cost: a coordinate update changes ONE coordinate, so only that
coordinate's files are re-serialized; every other coordinate directory (and
the best-model snapshot when unchanged) is hard-linked from the previous
version — per-update checkpoint cost is O(updated coordinate), not O(model).

``fingerprint``: an opaque caller-supplied string (hash of the config grid /
coordinate order) stored in the cursor and surfaced on load, so a resume
against a CHANGED configuration can be rejected instead of silently applying
a positional cursor to the wrong grid.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
from typing import Dict, Optional, Tuple

from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.data.reader import EntityIndex
from photon_ml_tpu.evaluation.evaluator import EvaluationResults
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.storage.model_io import (FORMAT_VERSION, coordinate_rel_dir,
                                            load_game_model, save_coordinate,
                                            save_game_model)
from photon_ml_tpu.types import TaskType

_POINTER = "LATEST"
_BEST = "best-model"


def _read_pointer(ckpt_dir: str) -> Optional[str]:
    try:
        with open(os.path.join(ckpt_dir, _POINTER)) as f:
            return f.read().strip()
    except FileNotFoundError:
        return None


def _link_tree(src: str, dst: str) -> None:
    """Hard-link a directory tree (fallback to copy on cross-device/EPERM)."""
    try:
        shutil.copytree(src, dst, copy_function=os.link)
    except OSError:
        shutil.rmtree(dst, ignore_errors=True)
        shutil.copytree(src, dst)


def save_checkpoint(
    ckpt_dir: str,
    model: GameModel,
    index_maps: Dict[str, IndexMap],
    cursor: Dict[str, int],
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
    task: TaskType = TaskType.LOGISTIC_REGRESSION,
    updated_coordinate: Optional[str] = None,
    best: Optional[Tuple[GameModel, EvaluationResults]] = None,
    best_changed: bool = True,
    fingerprint: Optional[str] = None,
    fmt: str = "avro",
) -> None:
    """``cursor``: {"iteration": i, "coordinate": k} — the NEXT update to run.

    ``updated_coordinate``: when given and a previous version exists, only
    that coordinate is re-serialized; the rest hard-link to the previous
    version.  ``best``: best-so-far (model, evaluation) retained across
    resume; re-serialized only when ``best_changed``.

    ``fmt``: model serialization format (see model_io.save_coordinate) —
    "columnar" makes per-update checkpoints O(1)-Python at huge d.  A format
    change invalidates prev-version coordinate reuse (no cross-format links).
    """
    os.makedirs(ckpt_dir, exist_ok=True)
    prev = _read_pointer(ckpt_dir)
    prev_dir = os.path.join(ckpt_dir, prev) if prev else None
    if prev_dir is not None and not os.path.isdir(prev_dir):
        prev_dir = None
    # Version = max existing v<N> + 1, NOT pointer+1: a crash between the
    # version rename and the pointer swap leaves an orphaned v<N+1>, and
    # deriving from the pointer would collide with it forever after.
    existing = [int(name[1:]) for name in os.listdir(ckpt_dir)
                if re.fullmatch(r"v\d+", name)]
    version = f"v{max(existing, default=0) + 1}"

    tmp = tempfile.mkdtemp(prefix=".tmp-", dir=ckpt_dir)
    try:
        prev_meta = None
        if prev_dir is not None:
            with open(os.path.join(prev_dir, "metadata.json")) as f:
                prev_doc = json.load(f)
            if prev_doc.get("format", "avro") == fmt:
                prev_meta = prev_doc["coordinates"]
            else:
                prev_dir = None  # format changed: never link old-format files
        meta = {"version": FORMAT_VERSION, "task": task.value, "coordinates": {}}
        if fmt == "columnar":
            meta["format"] = "columnar"
        for cid, m in model.models.items():
            rel = coordinate_rel_dir(cid, m)
            src = os.path.join(prev_dir, rel) if prev_dir is not None else None
            if (updated_coordinate is not None and cid != updated_coordinate
                    and src is not None and os.path.isdir(src)):
                _link_tree(src, os.path.join(tmp, rel))
                meta["coordinates"][cid] = prev_meta[cid]
            else:
                meta["coordinates"][cid] = save_coordinate(
                    cid, m, tmp, index_maps, entity_indexes, fmt=fmt)
        with open(os.path.join(tmp, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=2)

        if best is not None:
            best_model, best_eval = best
            bdir = os.path.join(tmp, _BEST)
            prev_best = (os.path.join(prev_dir, _BEST)
                         if prev_dir is not None else None)
            # common case during the improving phase: the new best IS the
            # current model — link the coordinate trees just written above
            # instead of re-serializing the whole model
            best_is_current = (best_model.models.keys() == model.models.keys()
                               and all(best_model.models[k] is model.models[k]
                                       for k in model.models))
            if not best_changed and prev_best is not None and os.path.isdir(prev_best):
                _link_tree(prev_best, bdir)
            elif best_is_current:
                os.makedirs(bdir, exist_ok=True)
                for cid, m in model.models.items():
                    rel = coordinate_rel_dir(cid, m)
                    os.makedirs(os.path.dirname(os.path.join(bdir, rel)), exist_ok=True)
                    _link_tree(os.path.join(tmp, rel), os.path.join(bdir, rel))
                shutil.copyfile(os.path.join(tmp, "metadata.json"),
                                os.path.join(bdir, "metadata.json"))
            else:
                save_game_model(best_model, bdir, index_maps, entity_indexes,
                                task, fmt=fmt)
        cursor_doc = dict(cursor)
        if fingerprint is not None:
            cursor_doc["fingerprint"] = fingerprint
        if best is not None:
            cursor_doc["best_eval"] = {"values": best[1].values,
                                       "primary_name": best[1].primary_name}
        with open(os.path.join(tmp, "cursor.json"), "w") as f:
            json.dump(cursor_doc, f)
        os.rename(tmp, os.path.join(ckpt_dir, version))  # atomic: new name
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise

    # atomic pointer swap, then prune every superseded/orphaned version
    ptr_tmp = os.path.join(ckpt_dir, f".{_POINTER}.tmp")
    with open(ptr_tmp, "w") as f:
        f.write(version)
        f.flush()
        os.fsync(f.fileno())
    os.replace(ptr_tmp, os.path.join(ckpt_dir, _POINTER))
    for name in os.listdir(ckpt_dir):
        stale = (re.fullmatch(r"v\d+", name) and name != version) or name.startswith(".tmp-")
        if stale:
            shutil.rmtree(os.path.join(ckpt_dir, name), ignore_errors=True)


def has_checkpoint(ckpt_dir: str) -> bool:
    """True when a checkpoint pointer exists (the only state meaning
    'something was saved here' — saves are atomic, so a present pointer
    names a fully-written version)."""
    return _read_pointer(ckpt_dir) is not None


def load_checkpoint(
    ckpt_dir: str,
    index_maps: Dict[str, IndexMap],
    entity_indexes: Optional[Dict[str, EntityIndex]] = None,
) -> Tuple[GameModel, TaskType, Dict[str, int],
           Optional[Tuple[GameModel, EvaluationResults]]]:
    """Returns (model, task, cursor, best) — ``best`` is the retained
    best-so-far (model, evaluation) or None.  ``cursor`` carries the saved
    ``fingerprint`` (if any) for the caller to validate against its config."""
    version = _read_pointer(ckpt_dir)
    if version is None:
        raise FileNotFoundError(f"no checkpoint pointer in {ckpt_dir}")
    vdir = os.path.join(ckpt_dir, version)
    model, task = load_game_model(vdir, index_maps, entity_indexes)
    with open(os.path.join(vdir, "cursor.json")) as f:
        cursor = json.load(f)
    best = None
    best_eval_doc = cursor.pop("best_eval", None)
    bdir = os.path.join(vdir, _BEST)
    if best_eval_doc is not None and os.path.isdir(bdir):
        best_model, _ = load_game_model(bdir, index_maps, entity_indexes)
        best = (best_model, EvaluationResults(values=best_eval_doc["values"],
                                              primary_name=best_eval_doc["primary_name"]))
    return model, task, cursor, best
