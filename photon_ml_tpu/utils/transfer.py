"""Chunked host->device transfer for slow / fragile transports.

The axon tunnel moves bytes at hundreds of KB/s, and its first observed
degradation followed the bench's first ~512MB single-shot design-matrix
upload (BASELINE.md round-3 notes).  A monolithic ``jnp.asarray(big)``
gives the transport one giant buffer to swallow with no observability; this
helper slices the leading axis into ~chunk_bytes pieces, blocks after each,
and assembles on device — same bytes, but each RPC is bounded, progress is
loggable, and a mid-transfer failure surfaces at the failing chunk instead
of an opaque hang.

Assembly uses a DONATED ``lax.dynamic_update_slice`` per chunk, so the
device-memory peak is output + one chunk — NOT output + all chunks as a
``jnp.concatenate`` would give (the design matrix must never be
double-resident in HBM; see the storage-narrowing note at its call site in
game/coordinate.py).

Used for arrays above PHOTON_CHUNKED_PUT_MIN_MB (default 64; 0 disables
chunking).  Covers every design-matrix upload: fixed-effect dense/sparse
shards AND random-effect full-sample arrays route through here.  The
reference has no analog — Spark ships partitions to executors; here the
full design matrix rides HBM (SURVEY.md §2.7 broadcast -> SPMD
replication) and these are the places those bytes cross the wire.
"""

from __future__ import annotations

import logging
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from photon_ml_tpu.obs import get_probe
from photon_ml_tpu.obs import trace as _trace

_LOG = logging.getLogger("photon_ml_tpu.transfer")


def _min_bytes() -> int:
    return int(float(os.environ.get("PHOTON_CHUNKED_PUT_MIN_MB", "64"))
               * 1024 * 1024)


# Module-level jit: one wrapper, one compile per (shape-pair) — a fresh
# jax.jit per chunk would retrace/recompile identical programs chunk after
# chunk, each an extra control-plane RPC on the transport this exists to
# relieve.  The start index is traced, so successive offsets reuse the
# compiled program; only the ragged last chunk adds a second compile.
_UPDATE = jax.jit(lax.dynamic_update_slice, donate_argnums=0)


def _update_at(out: jax.Array, part: jax.Array, lo: int,
               axis: int) -> jax.Array:
    """Donated slice write along ``axis``: reuses ``out``'s buffer, so
    assembling N chunks never holds more than output + one chunk on device."""
    start = tuple(lo if a == axis else 0 for a in range(out.ndim))
    # photonlint: disable=donation-after-use -- documented consuming
    # contract: chunked_device_put owns ``out`` and immediately rebinds it
    # (out = _update_at(out, ...)); donating the caller's buffer is the
    # point — the device peak stays output + one chunk
    return _UPDATE(out, part, start)


def stream_device_put(arr: np.ndarray, dtype=None) -> jax.Array:
    """Non-blocking upload of one fixed-shape stream-feed batch.

    The streaming data plane's upload primitive: unlike
    ``chunked_device_put`` it never blocks (double-buffering wants the
    transfer in flight while the decode pool fills the next batch) and never
    chunks (feed batches are already bounded by ``batch_rows``).  Every
    upload is probe-accounted under ``site="stream_feed"`` so the bench's
    ingest-bytes axis and the photonscope byte counters agree.
    """
    arr = np.asarray(arr, dtype)
    get_probe().record_transfer(arr.nbytes, "h2d", site="stream_feed")
    with _trace.span("stream.upload", bytes=int(arr.nbytes)):
        return jnp.asarray(arr)


def stream_update(out: jax.Array, part: jax.Array, lo: int,
                  rows: int) -> jax.Array:
    """Donated write of a (possibly padded) stream batch at row ``lo``.

    ``rows`` is the batch's VALID row count; pow2-padded batches are sliced
    to it first because ``lax.dynamic_update_slice`` CLAMPS out-of-range
    start indices — writing a padded tail block at a clamped start would
    silently overwrite the rows before it.  The slice costs one extra
    ``_UPDATE`` compile for the single tail shape; every full batch reuses
    the one program (start index is traced).
    """
    if rows != part.shape[0]:
        part = part[:rows]
    # photonlint: disable=donation-after-use -- documented consuming
    # contract: DeviceFeed owns ``out`` and immediately rebinds it
    # (self._out[gid] = stream_update(self._out[gid], ...)); donating keeps
    # the device peak at output + in-flight batches across the whole stream
    return _update_at(out, part, lo, 0)


def chunked_device_put(arr: np.ndarray, dtype=None,
                       chunk_bytes: int = 32 * 1024 * 1024) -> jax.Array:
    """``jnp.asarray(np.asarray(arr, dtype))`` with bounded transfer RPCs.

    Small arrays (below PHOTON_CHUNKED_PUT_MIN_MB) and rank-0 arrays take
    the direct path; large ones upload in leading-axis slices of about
    ``chunk_bytes`` each (always >=1 row), written into a preallocated
    device buffer via donation.
    """
    if isinstance(arr, jax.Array):
        # Already device-resident (e.g. one upload shared across bench A/B
        # variants): never round-trip through host. Dtype mismatch casts
        # on device — transiently double-resident, so callers that care
        # about storage narrowing should upload narrowed host bytes instead.
        want = jnp.dtype(dtype) if dtype is not None else arr.dtype
        return arr if arr.dtype == want else arr.astype(want)
    arr = np.asarray(arr, dtype)
    min_bytes = _min_bytes()
    # chunk along the LARGEST axis: a transposed narrow array ([d, n] —
    # score_samples_t's samples-on-lanes layout) has a tiny leading axis,
    # and leading-axis-only chunking would silently fall back to the one
    # giant RPC this helper exists to prevent
    axis = int(np.argmax(arr.shape)) if arr.ndim else 0
    probe = get_probe()
    if min_bytes <= 0 or arr.nbytes <= min_bytes or arr.ndim == 0 or \
            arr.shape[axis] <= 1:
        probe.record_transfer(arr.nbytes, "h2d", site="direct_put")
        return jnp.asarray(arr)
    row_bytes = max(1, arr.nbytes // arr.shape[axis])
    rows = max(1, chunk_bytes // row_bytes)
    t0 = time.perf_counter()
    with _trace.span("transfer.chunked_put", bytes=int(arr.nbytes)):
        out = jnp.zeros(arr.shape, arr.dtype)
        n_chunks = 0
        for lo in range(0, arr.shape[axis], rows):
            sel = tuple(slice(lo, lo + rows) if a == axis else slice(None)
                        for a in range(arr.ndim))
            part = jnp.asarray(arr[sel])
            part.block_until_ready()
            # per-chunk accounting: a mid-transfer stall shows up as byte
            # counters that stopped growing, not an opaque hang
            probe.record_transfer(part.nbytes, "h2d", site="chunked_put")
            out = _update_at(out, part, lo, axis)
            n_chunks += 1
        out.block_until_ready()
    dt = time.perf_counter() - t0
    _LOG.info("chunked_device_put: %.1fMB in %d chunks, %.1fs (%.2fMB/s)",
              arr.nbytes / 1e6, n_chunks, dt, arr.nbytes / 1e6 / max(dt, 1e-9))
    return out
