"""Training lifecycle event pub-sub.

Reference: photon-client .../event/{Event.scala:64, EventEmitter.scala:20-73,
EventListener.scala:32} — drivers emit lifecycle events (training start/end,
phase transitions, metric reports) to listeners registered by class name via
reflection.  Here listeners register as callables or ``EventListener``
subclasses; name-based registration resolves ``module:Class`` strings so CLI
flags can wire listeners the way the reference's reflection path did.

Tracer bridge: emitted events also land on the shared observability
timeline as instant events (``obs.instant``), so lifecycle listeners and
the trace see ONE sequence of ticks — disabled tracing costs one boolean
check per emit; ``EventEmitter(trace=False)`` opts a noisy emitter out.
"""

from __future__ import annotations

import dataclasses
import importlib
import time
from typing import Any, Callable, Dict, List, Union

from photon_ml_tpu.obs import trace as _trace


@dataclasses.dataclass(frozen=True)
class Event:
    """A lifecycle event (reference Event.scala): name + payload + timestamp."""

    name: str
    payload: Dict[str, Any] = dataclasses.field(default_factory=dict)
    timestamp: float = dataclasses.field(default_factory=time.time)


class EventListener:
    """Listener contract (reference EventListener.scala:32)."""

    def on_event(self, event: Event) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self) -> None:
        pass


class _CallableListener(EventListener):
    def __init__(self, fn: Callable[[Event], None]):
        self._fn = fn

    def on_event(self, event: Event) -> None:
        self._fn(event)


class EventEmitter:
    """Emitter mixin/base (reference EventEmitter.scala:20-73).

    ``register`` accepts an ``EventListener``, a plain callable, or a
    ``"module.path:ClassName"`` string (the reference registers listener
    classes by reflected name, Driver.scala:95-104).
    """

    def __init__(self, trace: bool = True) -> None:
        self._listeners: List[EventListener] = []
        self._trace = trace

    def register(self, listener: Union[EventListener, Callable[[Event], None], str]) -> EventListener:
        if isinstance(listener, str):
            module_name, sep, class_name = listener.partition(":")
            if not sep or not class_name:
                raise ValueError(
                    f"listener spec {listener!r} must be 'module.path:ClassName'")
            cls = getattr(importlib.import_module(module_name), class_name)
            listener = cls()
        if not isinstance(listener, EventListener):
            listener = _CallableListener(listener)
        self._listeners.append(listener)
        return listener

    def emit(self, name: str, **payload: Any) -> Event:
        event = Event(name=name, payload=payload)
        if self._trace:
            # lifecycle ticks share the span timeline (instant events);
            # payloads ride as args, stringified only at export time
            _trace.instant(name, **payload)
        for listener in self._listeners:
            listener.on_event(event)
        return event

    def close_listeners(self) -> None:
        for listener in self._listeners:
            listener.close()
        self._listeners.clear()
