"""Dense linear-algebra helpers.

Reference: util/Linalg.scala:104 ``choleskyInverse`` (used for FULL variance:
diag(H^-1) via Cholesky, DistributedOptimizationProblem.scala:84-108) — there
backed by netlib-java LAPACK; here by XLA's ``cholesky`` +
``triangular_solve`` so it runs on-device and fuses under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array


def cholesky_inverse(a: Array, jitter: float = 0.0) -> Array:
    """Inverse of a symmetric positive-definite matrix via Cholesky.

    ``jitter`` adds ``jitter * I`` first (GP kernel matrices need it;
    reference GaussianProcessEstimator adds a noise nugget).
    """
    a = jnp.asarray(a)
    if jitter:
        a = a + jitter * jnp.eye(a.shape[-1], dtype=a.dtype)
    chol = jnp.linalg.cholesky(a)
    eye = jnp.eye(a.shape[-1], dtype=a.dtype)
    inv_l = jax.scipy.linalg.solve_triangular(chol, eye, lower=True)
    return inv_l.T @ inv_l


def solve_psd(a: Array, b: Array, jitter: float = 0.0) -> Array:
    """Solve ``a x = b`` for symmetric positive-definite ``a`` via Cholesky."""
    a = jnp.asarray(a)
    if jitter:
        a = a + jitter * jnp.eye(a.shape[-1], dtype=a.dtype)
    chol = jnp.linalg.cholesky(a)
    y = jax.scipy.linalg.solve_triangular(chol, b, lower=True)
    return jax.scipy.linalg.solve_triangular(chol.T, y, lower=False)
