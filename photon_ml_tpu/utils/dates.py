"""Date-range input resolution for daily-partitioned datasets.

Reference: photon-client util/DateRange.scala (DEFAULT_PATTERN "yyyyMMdd",
split on "-", :39-83), util/DaysRange.scala (days-ago pair, toDateRange
:43-48, :64-67), and util/IOUtils.getInputPathsWithinDateRange:113-153
(expand ``<base>/yyyy/MM/dd`` per day, filter missing dirs, optionally
error on missing, require at least one match).
"""

from __future__ import annotations

import dataclasses
import datetime
import os
from typing import Iterable, List, Optional, Sequence

_PATTERN = "%Y%m%d"  # reference DateRange.DEFAULT_PATTERN "yyyyMMdd"
_DELIMITER = "-"


@dataclasses.dataclass(frozen=True)
class DateRange:
    """Inclusive [start, end] calendar-day range (DateRange.scala:28-34)."""

    start: datetime.date
    end: datetime.date

    def __post_init__(self):
        if self.start > self.end:
            raise ValueError(
                f"invalid date range: start {self.start} is after end {self.end}")

    @classmethod
    def from_string(cls, range_str: str) -> "DateRange":
        """Parse ``yyyyMMdd-yyyyMMdd`` (DateRange.fromDateString:70-76)."""
        start_str, end_str = _split_range(range_str)
        try:
            start = datetime.datetime.strptime(start_str, _PATTERN).date()
            end = datetime.datetime.strptime(end_str, _PATTERN).date()
        except ValueError as e:
            raise ValueError(f"couldn't parse date range '{range_str}': {e}") from e
        return cls(start, end)

    def days(self) -> List[datetime.date]:
        n = (self.end - self.start).days
        return [self.start + datetime.timedelta(days=i) for i in range(n + 1)]

    def __str__(self) -> str:
        return (self.start.strftime(_PATTERN) + _DELIMITER
                + self.end.strftime(_PATTERN))


@dataclasses.dataclass(frozen=True)
class DaysRange:
    """Range in days-ago-from-today, e.g. ``90-1`` = from 90 days ago to
    yesterday (DaysRange.scala:28-48).  start_days > end_days because the
    larger days-ago value is further in the past."""

    start_days: int
    end_days: int

    def __post_init__(self):
        if self.start_days < self.end_days:
            raise ValueError(
                f"invalid days range: start {self.start_days} must be >= end "
                f"{self.end_days} (days ago, larger = further back)")
        if self.end_days < 0:
            raise ValueError("days-ago values must be non-negative")

    @classmethod
    def from_string(cls, range_str: str) -> "DaysRange":
        start_str, end_str = _split_range(range_str)
        return cls(int(start_str), int(end_str))

    def to_date_range(self, today: Optional[datetime.date] = None) -> DateRange:
        """DaysRange.toDateRange:43-48."""
        today = today or datetime.date.today()
        return DateRange(today - datetime.timedelta(days=self.start_days),
                         today - datetime.timedelta(days=self.end_days))

    def __str__(self) -> str:
        return f"{self.start_days}{_DELIMITER}{self.end_days}"


def _split_range(range_str: str) -> Sequence[str]:
    """DateRange.splitRange:83-85."""
    parts = range_str.split(_DELIMITER)
    if len(parts) != 2:
        raise ValueError(f"couldn't parse range '{range_str}': expected "
                         f"'start{_DELIMITER}end'")
    return parts


def resolve_range(date_range: Optional[str],
                  days_range: Optional[str],
                  today: Optional[datetime.date] = None) -> Optional[DateRange]:
    """IOUtils.resolveRange:47-61: at most one of the two may be given;
    a days range converts relative to today."""
    if date_range and days_range:
        raise ValueError("specify at most one of date range / days range")
    if date_range:
        return DateRange.from_string(date_range)
    if days_range:
        return DaysRange.from_string(days_range).to_date_range(today)
    return None


def input_paths_within_date_range(base_dirs: Iterable[str],
                                  date_range: DateRange,
                                  error_on_missing: bool = False) -> List[str]:
    """Expand each base dir to its existing ``<base>/yyyy/MM/dd`` daily dirs
    within the inclusive range (IOUtils.getInputPathsWithinDateRange:113-153).

    Missing daily dirs are skipped unless ``error_on_missing``; it is an
    error for a base dir to contribute no day at all.
    """
    out: List[str] = []
    for base in base_dirs:
        candidates = [os.path.join(base, d.strftime("%Y/%m/%d"))
                      for d in date_range.days()]
        if error_on_missing:
            missing = [p for p in candidates if not os.path.exists(p)]
            if missing:
                raise FileNotFoundError(f"path {missing[0]} does not exist")
        existing = [p for p in candidates if os.path.exists(p)]
        if not existing:
            raise FileNotFoundError(
                f"no data folder found between {date_range.start} and "
                f"{date_range.end} in {base}")
        out.extend(existing)
    return out
