"""Persistent XLA compilation cache.

TPU first-compiles of the while_loop-heavy solvers are tens of seconds; the
persistent cache makes every LATER process (reruns, scoring after training,
benchmarks) hit compiled binaries instead.  The reference's analog is the
JVM warming Spark executors once per application — here the warmth survives
across processes on disk.

Env override: ``PHOTON_COMPILE_CACHE=<dir>`` relocates it, ``=0`` disables.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger(__name__)

def _host_tag() -> str:
    """Short stable id of THIS machine's CPU capabilities.

    XLA:CPU AOT executables bake target-machine features; loading a cache
    written on a different host warns "+prefer-no-scatter ... not supported
    on the host machine ... could lead to execution errors such as SIGILL"
    (observed when the build environment migrated between rounds).  Keying
    the cache dir by a hash of the cpuinfo flags gives each machine type its
    own cache instead of sharing stale foreign binaries."""
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            for ln in f:
                # x86 writes "flags", aarch64 writes "Features"
                if ln.startswith(("flags", "Features")):
                    return hashlib.sha256(
                        " ".join(sorted(ln.split()[2:])).encode()
                    ).hexdigest()[:10]
    except OSError:
        pass
    import platform

    # machine() is never empty ("x86_64"/"arm64"); processor() often is —
    # hash both so hosts without a parseable cpuinfo at least split by
    # architecture instead of silently sharing one tag
    return hashlib.sha256(
        f"{platform.machine()}|{platform.processor()}".encode()
    ).hexdigest()[:10]


def _default_dir() -> str:
    # Source checkout: repo-root .xla_cache (the package's grandparent holds
    # the repo's own files).  Installed package: user cache dir — the
    # grandparent is site-packages' parent, which must not be littered.
    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    if os.path.exists(os.path.join(root, "photon_ml_tpu", "__init__.py")) \
            and not os.path.basename(root).endswith("-packages") \
            and os.access(root, os.W_OK):
        return os.path.join(root, ".xla_cache", _host_tag())
    return os.path.join(os.path.expanduser("~"), ".cache", "photon_ml_tpu",
                        "xla", _host_tag())


def enable_compilation_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Turn on jax's persistent compilation cache; returns the dir (or None
    when disabled).  Safe to call multiple times / after jax is initialized.

    Cache residency reports into the observability registry (gauge
    ``xla_compile_cache_enabled`` + a trace instant): a silently-disabled
    cache means every process pays full first-compiles, which must be
    visible next to the ``jax_compiles_total`` counters it inflates."""
    from photon_ml_tpu.obs import get_probe

    env = os.environ.get("PHOTON_COMPILE_CACHE")
    if env == "0":
        get_probe().record_compile_cache(False)
        return None
    cache_dir = cache_dir or env or _default_dir()
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache everything that took noticeable compile time
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        get_probe().record_compile_cache(True, cache_dir)
        return cache_dir
    except Exception as e:  # never let cache setup break a run
        logger.warning("compilation cache unavailable: %s", e)
        get_probe().record_compile_cache(False)
        return None
