"""Job logging + phase timing.

Reference: util/PhotonLogger.scala (slf4j logger writing a job log file
alongside the job outputs, with level control) and util/Timed.scala:25-77
(``Timed { ... }`` blocks wrapping every pipeline phase, logging durations).

TPU-native notes: ``Timed`` measures wall clock of the enclosed host block,
which is what the reference measures too; device-accurate timings come from
the tracer's opt-in per-span fences (``obs.span(..., device_sync=True)``).
Every ``Timed`` block also runs as a tracer span (one timing path, two
sinks: the log line and the shared timeline) — when tracing is disabled the
hook is a single boolean check.
"""

from __future__ import annotations

import contextlib
import functools
import logging
import os
import time
from typing import Callable, Iterator, Optional

from photon_ml_tpu.obs import trace as _trace

_FORMAT = "%(asctime)s %(levelname)s %(name)s %(message)s"


_instance_counter = 0


class PhotonLogger:
    """A named logger that mirrors records to a job-log file.

    Reference util/PhotonLogger.scala: a logger instantiated per driver run
    writing to ``<output>/log-message.txt`` on HDFS (GameTrainingDriver.scala:
    840-841).  Here: a stdlib logger plus a ``FileHandler`` on the local/job
    filesystem; ``close()`` detaches the handler (HDFS flush equivalent).

    Each instance gets its own logger by default (one logger per driver run,
    as in the reference) so concurrent/sequential jobs in one process do not
    cross-write each other's log files; pass ``name`` to share deliberately.
    """

    def __init__(self, log_path: Optional[str] = None,
                 name: Optional[str] = None, level: int = logging.INFO):
        if name is None:
            global _instance_counter
            _instance_counter += 1
            name = f"photon_ml_tpu.job{_instance_counter}"
        self.logger = logging.getLogger(name)
        self.logger.setLevel(level)
        self._handler: Optional[logging.Handler] = None
        if log_path is not None:
            os.makedirs(os.path.dirname(os.path.abspath(log_path)), exist_ok=True)
            self._handler = logging.FileHandler(log_path)
            self._handler.setFormatter(logging.Formatter(_FORMAT))
            self.logger.addHandler(self._handler)

    def set_level(self, level: int) -> None:
        self.logger.setLevel(level)

    def debug(self, msg: str, *args) -> None:
        self.logger.debug(msg, *args)

    def info(self, msg: str, *args) -> None:
        self.logger.info(msg, *args)

    def warning(self, msg: str, *args) -> None:
        self.logger.warning(msg, *args)

    def error(self, msg: str, *args) -> None:
        self.logger.error(msg, *args)

    def close(self) -> None:
        if self._handler is not None:
            self.logger.removeHandler(self._handler)
            self._handler.close()
            self._handler = None

    def __enter__(self) -> "PhotonLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


@contextlib.contextmanager
def Timed(label: str, logger: Optional[logging.Logger] = None,
          sink: Optional[Callable[[str, float], None]] = None) -> Iterator[None]:
    """``with Timed("phase"):`` — log the phase duration (Timed.scala:25-77).

    The block is also a tracer span, so ``Timed`` phases land on the same
    nested timeline as the serving/descent spans instead of keeping a
    parallel timing path."""
    log = logger or logging.getLogger("photon_ml_tpu.timed")
    start = time.perf_counter()
    with _trace.span(label):
        try:
            yield
        finally:
            seconds = time.perf_counter() - start
            log.info("%s: %.3fs", label, seconds)
            if sink is not None:
                sink(label, seconds)


def timed(label: Optional[str] = None, logger: Optional[logging.Logger] = None):
    """Decorator form of ``Timed`` for pipeline-phase functions."""

    def wrap(fn: Callable) -> Callable:
        name = label or fn.__qualname__

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with Timed(name, logger):
                return fn(*args, **kwargs)

        return inner

    return wrap
