"""Utilities: structured job logging, timing, lifecycle events, linalg helpers.

Reference: photon-lib .../util/{PhotonLogger,Timed,Linalg}.scala and
photon-client .../event/{Event,EventEmitter,EventListener}.scala.
"""

from photon_ml_tpu.utils.logging import PhotonLogger, Timed, timed  # noqa: F401
from photon_ml_tpu.utils.events import Event, EventEmitter, EventListener  # noqa: F401
from photon_ml_tpu.utils.linalg import cholesky_inverse  # noqa: F401
