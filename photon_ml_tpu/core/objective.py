"""GLM objective: weighted-sum pointwise loss over a batch + smooth regularization.

Reference contract: photon-lib .../function/ObjectiveFunction.scala:25-74
(value / gradient / hessianVector / hessianDiagonal / hessianMatrix) with the
four aggregators (ValueAndGradientAggregator.scala, HessianVectorAggregator.scala,
HessianDiagonalAggregator.scala:128, HessianMatrixAggregator.scala:129).

Where the reference streams examples through mutable aggregator objects and
merges them via Spark ``treeAggregate``, here each quantity is one closed-form
batched expression — XLA fuses the elementwise loss into the margin matmul, and
the distributed version is exactly this code inside ``shard_map`` + ``psum``
(see photon_ml_tpu.parallel).  The abstract ``Data``/``Coefficients`` duality of
the reference (RDD vs local Iterable, ObjectiveFunction.scala:27-28) collapses:
the SAME function is psum'd across the mesh for fixed effects and ``vmap``-ed
over entity blocks for random effects.

Normalization follows the effective-coefficient + margin-shift algebra
(ValueAndGradientAggregator.scala:36-49) so the raw (sparse) design matrix is
never transformed; see core/normalization.py for the identities.

Note on semantics: objectives are weighted SUMS (not means), matching the
reference; convergence tolerances are relative so scale cancels.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.core.batch import Batch, DenseBatch, SparseBatch
from photon_ml_tpu.core.losses import PointwiseLoss
from photon_ml_tpu.core.normalization import NormalizationContext, no_normalization
from photon_ml_tpu.core.regularization import Regularization

Array = jax.Array


def _xt_dot(batch: Batch, r: Array, dim: int) -> Array:
    """X^T r against the raw design matrix (the gradient's scatter/reduce).

    Written as the LEFT product r @ X — the same contraction, but without an
    explicit transpose: XLA TPU folds either form into dot_general dimension
    numbers, while XLA *CPU* executes ``x.T @ r`` as a cache-hostile
    column-major walk (measured 20x slower than ``r @ x`` at [512k, 256] —
    the whole-solver fallback cost, since this runs once per L-BFGS/TRON
    function evaluation).

    Mixed precision mirrors DenseBatch.margins: narrow-stored x with MXU
    operands at storage width, accumulation/result at the residual's width."""
    if isinstance(batch, DenseBatch):
        if batch.x.dtype != r.dtype:
            return jnp.matmul(r.astype(batch.x.dtype), batch.x,
                              preferred_element_type=r.dtype)
        return r @ batch.x
    # Row-padded COO: scatter-add each value*r into its feature slot.  Padded
    # slots have value 0 so they contribute nothing wherever they point.
    contrib = batch.values.astype(r.dtype) * r[..., None]
    return jnp.zeros((dim,), contrib.dtype).at[batch.indices].add(contrib)


@struct.dataclass
class GLMObjective:
    """value / gradient / hvp / hessian_diag / hessian for one GLM coordinate.

    Pure-functional: all methods are (w, batch) -> arrays, jit/vmap/shard_map
    friendly.  ``loss`` and shapes are static; ``reg`` and ``norm`` are traced
    pytree leaves (so reg-path sweeps don't recompile).
    """

    loss: PointwiseLoss = struct.field(pytree_node=False)
    reg: Regularization = Regularization()
    norm: NormalizationContext = struct.field(default_factory=no_normalization)
    # Opt-in pallas fused kernels (ops/fused_glm.py): X streams through VMEM
    # once per value_and_grad / hvp instead of 2-3 XLA passes.  Dense batches
    # on TPU with lane-aligned dim only; silently identical math otherwise.
    fused: bool = struct.field(pytree_node=False, default=False)

    def with_reg(self, reg: Regularization) -> "GLMObjective":
        """Same objective, different (possibly traced) regularization weights
        — the vehicle for recompile-free reg-path sweeps."""
        return self.replace(reg=reg)

    @staticmethod
    def _fused_eligible(batch: Batch, w: Array = None) -> bool:
        """Trace-time gate for the pallas kernels; ineligible batches fall
        through to the reference XLA path below (single home for that math).

        Narrow float storage (bf16/f16 x against an f32 solver state) IS
        eligible: the callers cast the effective coefficients down to
        storage width, exactly mirroring DenseBatch.margins' mixed-precision
        contract (both MXU operands at storage width, f32 accumulation), so
        the kernel keeps the single-HBM-pass advantage at half the bytes.
        Any other dtype mix (e.g. f64 x / f32 w) stays on the XLA path."""
        from photon_ml_tpu.ops.fused_glm import eligible, storage_narrowing_ok

        if (w is not None and isinstance(batch, DenseBatch)
                and not storage_narrowing_ok(batch.x.dtype, w.dtype)):
            return False
        return eligible(batch)

    # -- margins ----------------------------------------------------------------

    def margins(self, w: Array, batch: Batch) -> Array:
        eff = self.norm.effective_coefficients(w)
        return batch.margins(eff) + batch.offset + self.norm.margin_shift(w)

    def _safe_margins(self, w: Array, batch: Batch) -> Array:
        """Margins with weight-0 (padded) rows zeroed.

        Guarantees the masking contract for unbounded losses: a garbage row
        with weight 0 must not poison reductions via 0 * inf = NaN (e.g.
        poisson exp(1e6)).  Zeroing z BEFORE the loss keeps every pointwise
        loss finite on padded rows.
        """
        z = self.margins(w, batch)
        return jnp.where(batch.weight > 0, z, 0.0)

    # -- objective value ---------------------------------------------------------

    def raw_value(self, w: Array, batch: Batch) -> Array:
        """Weighted loss sum, NO regularization (needed by eval / tracking)."""
        z = self._safe_margins(w, batch)
        return jnp.sum(batch.weight * self.loss.loss(z, batch.y))

    def l2_term(self, w: Array) -> Array:
        return 0.5 * self.reg.l2 * jnp.vdot(w, w)

    def l1_term(self, w: Array) -> Array:
        return self.reg.l1 * jnp.sum(jnp.abs(w))

    def value(self, w: Array, batch: Batch) -> Array:
        """Smooth objective: loss sum + L2 (L1 lives in OWLQN, as in reference)."""
        return self.raw_value(w, batch) + self.l2_term(w)

    # -- gradient ----------------------------------------------------------------

    def _chain(self, g_raw: Array, r_sum: Array) -> Array:
        """Apply normalization chain rule to a raw-space reduction X^T r.

        dmargin/dw = factor * (x - shift)  =>  g = factor*(X^T r - (Σr)·shift).
        """
        g = g_raw
        if self.norm.shifts is not None:
            g = g - r_sum * self.norm.shifts
        if self.norm.factors is not None:
            g = g * self.norm.factors
        return g

    def raw_value_and_grad(self, w: Array, batch: Batch) -> Tuple[Array, Array, Array]:
        """(Σ wt·l, X^T r, Σ r) — raw-space sums with NO regularization or
        normalization chain applied.  These are plain data-sums, so SPMD
        callers (parallel/fixed.ShardMapObjective) psum them across shards
        before finishing with ``finish_value_and_grad``."""
        if self.fused and self._fused_eligible(batch, w):
            from photon_ml_tpu.ops.fused_glm import fused_value_and_grad

            # storage-width effective coefficients: for narrow-stored x this
            # is DenseBatch.margins' mixed contract (bf16 MXU operands, f32
            # accumulation inside the kernel); a no-op for uniform dtypes
            eff = self.norm.effective_coefficients(w).astype(batch.x.dtype)
            raw_val, g_raw, r_sum = fused_value_and_grad(
                self.loss, eff, batch,
                margin_shift=self.norm.margin_shift(w))
            return (raw_val.astype(w.dtype), g_raw.astype(w.dtype),
                    r_sum.astype(w.dtype))
        z = self._safe_margins(w, batch)
        l, d1 = self.loss.loss_and_d1(z, batch.y)
        r = batch.weight * d1
        return (jnp.sum(batch.weight * l), _xt_dot(batch, r, w.shape[-1]),
                jnp.sum(r))

    def finish_value_and_grad(self, w: Array, raw_val: Array, g_raw: Array,
                              r_sum: Array) -> Tuple[Array, Array]:
        """Apply normalization chain rule + regularization to raw sums."""
        val = raw_val + self.l2_term(w)
        g = self._chain(g_raw, r_sum) + self.reg.l2 * w
        return val, g

    def value_and_grad(self, w: Array, batch: Batch) -> Tuple[Array, Array]:
        """Reference ValueAndGradientAggregator.calculateValueAndGradient:240-255,
        collapsed to one fused pass."""
        return self.finish_value_and_grad(w, *self.raw_value_and_grad(w, batch))

    def gradient(self, w: Array, batch: Batch) -> Array:
        return self.value_and_grad(w, batch)[1]

    # -- Hessian-vector product --------------------------------------------------

    def raw_hvp(self, w: Array, batch: Batch, v: Array) -> Tuple[Array, Array]:
        """(X^T q, Σ q) raw sums — psum-able like raw_value_and_grad."""
        if self.fused and self._fused_eligible(batch, w):
            from photon_ml_tpu.ops.fused_glm import fused_hvp

            # storage-width operands (see raw_value_and_grad)
            eff = self.norm.effective_coefficients(w).astype(batch.x.dtype)
            eff_v = self.norm.effective_coefficients(v).astype(batch.x.dtype)
            hv_raw, q_sum = fused_hvp(
                self.loss, eff, eff_v, batch,
                margin_shift=self.norm.margin_shift(w),
                v_shift=self.norm.margin_shift(v))
            return hv_raw.astype(w.dtype), q_sum.astype(w.dtype)
        z = self._safe_margins(w, batch)
        eff_v = self.norm.effective_coefficients(v)
        # margin directional derivative: factor*(x - shift)·v
        mv = batch.margins(eff_v)
        if self.norm.shifts is not None:
            mv = mv - jnp.vdot(eff_v, self.norm.shifts)
        q = batch.weight * self.loss.d2(z, batch.y) * mv
        return _xt_dot(batch, q, w.shape[-1]), jnp.sum(q)

    def finish_hvp(self, v: Array, hv_raw: Array, q_sum: Array) -> Array:
        return self._chain(hv_raw, q_sum) + self.reg.l2 * v

    def hvp(self, w: Array, batch: Batch, v: Array) -> Array:
        """H·v = Xn^T diag(weight · l'') Xn v + l2·v
        (reference HessianVectorAggregator.calcHessianVector:30-80)."""
        return self.finish_hvp(v, *self.raw_hvp(w, batch, v))

    # -- Hessian diagonal / full matrix (variance computation) --------------------

    def hessian_diag(self, w: Array, batch: Batch) -> Array:
        """diag(H) = Σ weight·l''·x'_j²  (reference HessianDiagonalAggregator.scala:128;
        unlike the reference, normalization IS supported)."""
        z = self._safe_margins(w, batch)
        q = batch.weight * self.loss.d2(z, batch.y)
        d = w.shape[-1]
        if isinstance(batch, DenseBatch):
            x2 = _xt_dot(batch.replace(x=batch.x * batch.x), q, d)
            x1 = _xt_dot(batch, q, d) if self.norm.shifts is not None else None
        else:
            b2 = batch.replace(values=batch.values * batch.values)
            x2 = _xt_dot(b2, q, d)
            x1 = _xt_dot(batch, q, d) if self.norm.shifts is not None else None
        diag = x2
        if self.norm.shifts is not None:
            s = self.norm.shifts
            diag = x2 - 2.0 * s * x1 + s * s * jnp.sum(q)
        if self.norm.factors is not None:
            diag = diag * self.norm.factors * self.norm.factors
        return diag + self.reg.l2

    def hessian(self, w: Array, batch: Batch) -> Array:
        """Full d×d Hessian (FULL variance only; reference
        HessianMatrixAggregator.scala:129).  Dense-materializes x — small d only."""
        dense = batch if isinstance(batch, DenseBatch) else batch.to_dense()
        z = jnp.where(dense.weight > 0, self.margins(w, dense), 0.0)
        q = dense.weight * self.loss.d2(z, dense.y)
        xn = dense.x
        if self.norm.shifts is not None:
            xn = xn - self.norm.shifts
        if self.norm.factors is not None:
            xn = xn * self.norm.factors
        h = (xn * q[:, None]).T @ xn
        return h + self.reg.l2 * jnp.eye(w.shape[-1], dtype=h.dtype)

    # -- predictions ---------------------------------------------------------------

    def scores(self, w: Array, batch: Batch) -> Array:
        """Raw margins (coordinate-descent residual currency)."""
        return self.margins(w, batch)

    def means(self, w: Array, batch: Batch) -> Array:
        """Inverse-link predictions (reference GeneralizedLinearModel.computeMean)."""
        return self.loss.mean(self.margins(w, batch))
