"""Feature-normalization algebra.

Reference: photon-lib .../normalization/NormalizationContext.scala:37-215 and the
aggregator algebra in function/glm/ValueAndGradientAggregator.scala:36-49.

The transform is affine per feature: x' = (x - shift) .* factor.  The key trick
(kept from the reference because it is also exactly what a TPU wants) is to never
materialize x': with

    eff(w)        = w .* factor                      ("effectiveCoefficients")
    margin_shift(w) = -dot(eff(w), shift)            ("totalShift")

we have  w·x' = eff(w)·x + margin_shift(w),  so margins — and, through autodiff,
gradients/Hessians — are computed against the RAW sparse/dense x.  The intercept
column has factor 1 / shift 0 by construction (factory below), matching
NormalizationContext.scala:137-186.

Coefficient-space maps (NormalizationContext.scala:73-124), margin-invariant:
  to original space:    w_j = w'_j * factor_j ;  b = b' - Σ_j w'_j factor_j shift_j
  to transformed space: w'_j = w_j / factor_j ;  b' = b + Σ_j w_j shift_j
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.types import NormalizationType

Array = jax.Array


@struct.dataclass
class FeatureStats:
    """Per-feature summary statistics (reference stat/FeatureDataStatistics.scala:139)."""

    mean: Array
    variance: Array
    min: Array
    max: Array
    abs_max: Array
    num_nonzeros: Array
    count: Array  # scalar: number of (weighted) examples
    intercept_index: Optional[int] = struct.field(pytree_node=False, default=None)


def compute_feature_stats(x: Array, weight: Optional[Array] = None,
                          intercept_index: Optional[int] = None) -> FeatureStats:
    """Dense-batch feature stats.

    Multihost/sharded: call jitted on a globally data-sharded array with the
    padded rows carrying weight 0 — the moment reductions become GSPMD
    cross-host collectives and every host sees identical global
    mean/variance/abs_max (what normalization consumes;
    tests/test_parallel.py::test_global_feature_stats_on_sharded_rows and
    the multihost recipe in parallel/multihost.py).  ALWAYS pass ``weight``
    in that setting: the unweighted branch divides by the padded row count.
    CAVEAT: ``min``/``max`` are order statistics weight cannot mask, so on
    padded data they include the pad rows' zeros."""
    n = x.shape[0]
    if weight is None:
        mean = jnp.mean(x, axis=0)
        var = jnp.var(x, axis=0, ddof=1) if n > 1 else jnp.zeros_like(mean)
        count = jnp.asarray(float(n), x.dtype)
    else:
        wsum = jnp.sum(weight)
        mean = jnp.sum(weight[:, None] * x, axis=0) / wsum
        var = jnp.sum(weight[:, None] * (x - mean) ** 2, axis=0) / jnp.maximum(wsum - 1.0, 1.0)
        count = wsum
    return FeatureStats(
        mean=mean,
        variance=var,
        min=jnp.min(x, axis=0),
        max=jnp.max(x, axis=0),
        abs_max=jnp.max(jnp.abs(x), axis=0),
        num_nonzeros=jnp.sum(x != 0, axis=0).astype(x.dtype),
        count=count,
        intercept_index=intercept_index,
    )


def compute_feature_stats_sparse(indices, values, dim: int,
                                 weight=None,
                                 intercept_index: Optional[int] = None
                                 ) -> FeatureStats:
    """Feature stats straight from row-padded COO arrays [n, k] — the
    huge-vocabulary twin of compute_feature_stats, so sparse shards can be
    normalized without densifying (reference BasicStatisticalSummary over
    sparse vectors).  Implicit zeros count toward every moment; padded slots
    (value 0) are inert.  Duplicate indices within a row make the
    second-moment stats approximate (Σv_i² vs (Σv_i)²) — the same tolerance
    the SparseShard contract grants SIMPLE-variance Hessian diagonals
    (game/data.py)."""
    import numpy as np

    idx = np.asarray(indices)
    val = np.asarray(values, np.float64)
    n, _ = idx.shape
    w = (np.ones(n, np.float64) if weight is None
         else np.asarray(weight, np.float64))
    wsum = float(w.sum())
    wv = w[:, None] * val
    s1 = np.zeros(dim, np.float64)   # Σ w x
    s2 = np.zeros(dim, np.float64)   # Σ w x²
    nnz = np.zeros(dim, np.float64)
    amax = np.zeros(dim, np.float64)
    np.add.at(s1, idx.ravel(), wv.ravel())
    np.add.at(s2, idx.ravel(), (wv * val).ravel())
    np.add.at(nnz, idx.ravel(), (val != 0).ravel())
    np.maximum.at(amax, idx.ravel(), np.abs(val).ravel())
    # min/max over nonzero observations, then blend in the implicit zero for
    # any column NOT observed (nonzero) in every row — a column present in
    # all n rows must report its true extremes, not 0
    vmin = np.full(dim, np.inf)
    vmax = np.full(dim, -np.inf)
    nz = val != 0
    np.minimum.at(vmin, idx[nz], val[nz])
    np.maximum.at(vmax, idx[nz], val[nz])
    rows_with = np.zeros(dim, np.int64)
    if nz.any():
        r = np.nonzero(nz)[0].astype(np.int64)
        # unique (row, col) pairs via one combined key — np.unique(axis=1)
        # would void-view sort, much slower at huge-vocabulary scale
        keys = np.unique(r * np.int64(dim) + idx[nz].astype(np.int64))
        np.add.at(rows_with, keys % np.int64(dim), 1)
    has_zero = rows_with < n
    vmin = np.where(has_zero, np.minimum(vmin, 0.0), vmin)
    vmax = np.where(has_zero, np.maximum(vmax, 0.0), vmax)
    mean = s1 / max(wsum, 1e-300)
    # weighted sample variance about the mean, implicit zeros included:
    # Σ w (x-m)² = Σ w x² - 2 m Σ w x + m² Σ w
    ss = s2 - 2.0 * mean * s1 + mean * mean * wsum
    var = np.maximum(ss, 0.0) / max(wsum - 1.0, 1.0)
    return FeatureStats(
        mean=jnp.asarray(mean), variance=jnp.asarray(var),
        min=jnp.asarray(vmin), max=jnp.asarray(vmax),
        abs_max=jnp.asarray(amax),
        num_nonzeros=jnp.asarray(nnz), count=jnp.asarray(wsum),
        intercept_index=intercept_index,
    )


@struct.dataclass
class NormalizationContext:
    """Affine feature normalization; ``factors``/``shifts`` may be None (identity).

    Replaces the reference's BroadcastWrapper plumbing (util/BroadcastWrapper.scala):
    under SPMD the arrays are simply replicated leaves of the jitted step's inputs.
    """

    factors: Optional[Array]  # [d] or None
    shifts: Optional[Array]  # [d] or None

    @property
    def is_identity(self) -> bool:
        return self.factors is None and self.shifts is None

    def effective_coefficients(self, w: Array) -> Array:
        return w if self.factors is None else w * self.factors

    def margin_shift(self, w: Array) -> Array:
        """-dot(eff(w), shift); add to every margin."""
        if self.shifts is None:
            return jnp.zeros((), w.dtype)
        return -jnp.vdot(self.effective_coefficients(w), self.shifts)

    def model_to_original_space(self, w: Array, intercept_index: Optional[int]) -> Array:
        """NormalizationContext.scala:73-99 — map transformed-space coefficients
        to original space, folding shift into the intercept."""
        out = self.effective_coefficients(w)
        if self.shifts is not None:
            if intercept_index is None:
                raise ValueError("shift normalization requires an intercept")
            out = out.at[intercept_index].add(-jnp.vdot(out, self.shifts))
        return out

    def model_to_transformed_space(self, w: Array, intercept_index: Optional[int]) -> Array:
        """NormalizationContext.scala:101-124 — inverse of the above."""
        out = w
        if self.shifts is not None:
            if intercept_index is None:
                raise ValueError("shift normalization requires an intercept")
            out = out.at[intercept_index].add(jnp.vdot(w, self.shifts))
        if self.factors is not None:
            out = out / self.factors
        return out


def no_normalization() -> NormalizationContext:
    """Reference NoNormalization."""
    return NormalizationContext(factors=None, shifts=None)


def build_normalization(kind: NormalizationType, stats: FeatureStats) -> NormalizationContext:
    """Factory from feature stats (reference NormalizationContext.scala:137-186).

    The intercept column keeps factor 1 / shift 0 so its coefficient is the
    actual intercept.
    """
    if kind == NormalizationType.NONE:
        return no_normalization()

    std = jnp.sqrt(stats.variance)
    safe = lambda a: jnp.where(a == 0.0, 1.0, a)  # features with no spread: factor 1

    if kind == NormalizationType.STANDARDIZATION and stats.intercept_index is None:
        # Shift normalization needs an intercept column to absorb the margin
        # shift or the model is not representable in original space; the
        # reference fails fast here too (NormalizationContext.scala:137-186
        # calls summary.interceptIndex.get).
        raise ValueError("STANDARDIZATION requires feature stats with an intercept_index")

    if kind == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        factors, shifts = 1.0 / safe(stats.abs_max), None
    elif kind == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        factors, shifts = 1.0 / safe(std), None
    elif kind == NormalizationType.STANDARDIZATION:
        factors, shifts = 1.0 / safe(std), stats.mean
    else:
        raise ValueError(f"unknown normalization type {kind!r}")

    ii = stats.intercept_index
    if ii is not None:
        factors = factors.at[ii].set(1.0)
        if shifts is not None:
            shifts = shifts.at[ii].set(0.0)
    return NormalizationContext(factors=factors, shifts=shifts)
