"""Pointwise GLM losses: l(z, y) at margin z = w·x + offset, with first and
second derivatives in z.

Reference contract: photon-lib .../function/glm/PointwiseLossFunction.scala:36-54
(``lossAndDzLoss``, ``DzzLoss``); concrete losses:
  - LogisticLossFunction.scala:45-90   (labels in {0,1}; stable log1pExp)
  - SquaredLossFunction.scala          (l = (z-y)^2 / 2)
  - PoissonLossFunction.scala          (l = exp(z) - y*z)
  - svm/SmoothedHingeLossFunction.scala:28-70 (Rennie smoothed hinge)

TPU-first design: each loss is a trio of pure elementwise functions over arrays —
XLA fuses them into the surrounding matmul/reduction; no per-example Scala-style
aggregator objects. Autodiff is NOT used for d1/d2 here because the reference
semantics (e.g. the smoothed hinge's sub-differential convention) must be exact,
and closed forms are cheaper under ``vmap`` + ``while_loop``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from photon_ml_tpu.types import TaskType

Array = jax.Array


def log1p_exp(z: Array) -> Array:
    """Numerically stable log(1 + exp(z)) (reference util/MathUtils.log1pExp)."""
    return jnp.logaddexp(0.0, z)


@dataclasses.dataclass(frozen=True)
class PointwiseLoss:
    """A pointwise loss l(z, y) with derivatives and the GLM mean (inverse link).

    Attributes:
      name: stable identifier (used in model metadata files).
      loss: elementwise l(z, y).
      d1:   elementwise dl/dz.
      d2:   elementwise d2l/dz2 (>= 0 for the convex losses here).
      mean: inverse link E[y|z] used for prediction
            (reference supervised/model/*Model.computeMean).
    """

    name: str
    loss: Callable[[Array, Array], Array]
    d1: Callable[[Array, Array], Array]
    d2: Callable[[Array, Array], Array]
    mean: Callable[[Array], Array]

    def loss_and_d1(self, z: Array, y: Array) -> tuple[Array, Array]:
        """Reference PointwiseLossFunction.lossAndDzLoss:36-54."""
        return self.loss(z, y), self.d1(z, y)


def _logistic_loss(z: Array, y: Array) -> Array:
    # l = log(1 + exp(z)) - y*z, stable for large |z|.  Reference
    # LogisticLossFunction.scala:45-90 (equivalent form with labels in {0,1}).
    return log1p_exp(z) - y * z


def _logistic_d1(z: Array, y: Array) -> Array:
    return jax.nn.sigmoid(z) - y


def _logistic_d2(z: Array, y: Array) -> Array:
    s = jax.nn.sigmoid(z)
    return s * (1.0 - s)


logistic_loss = PointwiseLoss(
    name="logistic",
    loss=_logistic_loss,
    d1=_logistic_d1,
    d2=_logistic_d2,
    mean=jax.nn.sigmoid,
)


def _squared_loss(z: Array, y: Array) -> Array:
    d = z - y
    return 0.5 * d * d


squared_loss = PointwiseLoss(
    name="squared",
    loss=_squared_loss,
    d1=lambda z, y: z - y,
    d2=lambda z, y: jnp.ones_like(z),
    mean=lambda z: z,
)


def _poisson_loss(z: Array, y: Array) -> Array:
    return jnp.exp(z) - y * z


poisson_loss = PointwiseLoss(
    name="poisson",
    loss=_poisson_loss,
    d1=lambda z, y: jnp.exp(z) - y,
    d2=lambda z, y: jnp.exp(z),
    mean=jnp.exp,
)


def _hinge_sign(y: Array) -> Array:
    # Labels arrive in {0,1}; the reference thresholds soft labels at 0.5 to
    # s in {-1,+1} (SmoothedHingeLossFunction.scala) — do the same.
    return jnp.where(y >= 0.5, 1.0, -1.0)


def _hinge_t(z: Array, y: Array) -> Array:
    return _hinge_sign(y) * z


def _smoothed_hinge_loss(z: Array, y: Array) -> Array:
    # Rennie's smoothed hinge (reference SmoothedHingeLossFunction.scala:28-70):
    #   t >= 1: 0;  t <= 0: 1/2 - t;  else: (1-t)^2 / 2.
    t = _hinge_t(z, y)
    quad = 0.5 * (1.0 - t) ** 2
    return jnp.where(t >= 1.0, 0.0, jnp.where(t <= 0.0, 0.5 - t, quad))


def _smoothed_hinge_d1(z: Array, y: Array) -> Array:
    s = _hinge_sign(y)
    t = s * z
    dldt = jnp.where(t >= 1.0, 0.0, jnp.where(t <= 0.0, -1.0, t - 1.0))
    return s * dldt


def _smoothed_hinge_d2(z: Array, y: Array) -> Array:
    # 1 inside the quadratic region, 0 outside (reference convention).
    t = _hinge_t(z, y)
    return jnp.where((t > 0.0) & (t < 1.0), 1.0, 0.0)


smoothed_hinge_loss = PointwiseLoss(
    name="smoothed_hinge",
    loss=_smoothed_hinge_loss,
    d1=_smoothed_hinge_d1,
    d2=_smoothed_hinge_d2,
    # Score-based classifier: "mean" is the raw margin, thresholded at 0
    # (reference SmoothedHingeLossLinearSVMModel).
    mean=lambda z: z,
)


_TASK_LOSS = {
    TaskType.LOGISTIC_REGRESSION: logistic_loss,
    TaskType.LINEAR_REGRESSION: squared_loss,
    TaskType.POISSON_REGRESSION: poisson_loss,
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: smoothed_hinge_loss,
}

_NAME_LOSS = {l.name: l for l in _TASK_LOSS.values()}


def loss_for_task(task: TaskType) -> PointwiseLoss:
    """Reference ObjectiveFunctionHelper.buildFactory per TaskType (:39-46)."""
    try:
        return _TASK_LOSS[task]
    except KeyError:
        raise ValueError(f"no pointwise loss for task {task!r}")


def loss_by_name(name: str) -> PointwiseLoss:
    try:
        return _NAME_LOSS[name]
    except KeyError:
        raise ValueError(f"unknown loss {name!r}; valid: {sorted(_NAME_LOSS)}")
