from photon_ml_tpu.core.losses import (  # noqa: F401
    PointwiseLoss,
    logistic_loss,
    squared_loss,
    poisson_loss,
    smoothed_hinge_loss,
    loss_for_task,
)
from photon_ml_tpu.core.batch import DenseBatch, SparseBatch, Batch  # noqa: F401
from photon_ml_tpu.core.normalization import (  # noqa: F401
    NormalizationContext,
    no_normalization,
    FeatureStats,
)
from photon_ml_tpu.core.regularization import Regularization  # noqa: F401
from photon_ml_tpu.core.objective import GLMObjective  # noqa: F401
