"""Regularization configuration.

Reference: photon-api .../optimization/RegularizationContext.scala:134 (the
elastic-net split: l1 = alpha * lambda, l2 = (1 - alpha) * lambda) and the
stackable L2 mixins in photon-lib .../function/L2Regularization.scala:26-200.

Here regularization is plain data threaded into the objective: the smooth L2
part joins value/gradient/Hessian; the L1 part is handled by the OWLQN solver's
orthant-wise machinery (as in the reference, where Breeze OWLQN owns L1).
"""

from __future__ import annotations

import enum

from flax import struct


class RegularizationType(enum.Enum):
    NONE = "none"
    L1 = "l1"
    L2 = "l2"
    ELASTIC_NET = "elastic_net"


@struct.dataclass
class Regularization:
    """Smooth + non-smooth regularization weights.

    ``l2`` adds (l2/2)·‖w‖² to the objective (L2Regularization.scala:26);
    ``l1`` adds l1·‖w‖₁, applied orthant-wise by OWLQN, never differentiated.
    """

    l1: float = 0.0
    l2: float = 0.0

    @classmethod
    def from_context(cls, kind: RegularizationType, weight: float, alpha: float = 1.0) -> "Regularization":
        """RegularizationContext.scala:134 semantics."""
        if kind == RegularizationType.NONE:
            return cls()
        if kind == RegularizationType.L1:
            return cls(l1=weight)
        if kind == RegularizationType.L2:
            return cls(l2=weight)
        if kind == RegularizationType.ELASTIC_NET:
            return cls(l1=alpha * weight, l2=(1.0 - alpha) * weight)
        raise ValueError(f"unknown regularization type {kind!r}")
