"""Batched training-data containers (pytrees).

Reference data model: LabeledPoint(label, features, offset, weight)
(photon-lib .../data/LabeledPoint.scala:106).  The reference streams one
LabeledPoint at a time through aggregator objects; on TPU the unit of work is a
statically-shaped batch so every margin is one matmul / gather on the MXU.

Two physical layouts:

- ``DenseBatch``:  x[n, d] — for moderate d or post-projection entity blocks.
- ``SparseBatch``: padded per-row COO (indices[n, k], values[n, k]) — for wide
  sparse data (CTR-style).  Rows pad with (index=0, value=0); zero values make
  padded slots contribute nothing to margins or gradients.  This replaces the
  reference's Breeze SparseVector path; gradient scatter-adds become XLA
  segment-sums through autodiff of the gather.

Padded/invalid examples carry weight 0 — the aggregation algebra (weighted sums
everywhere, reference ValueAndGradientAggregator.scala:137-161) then ignores
them with no separate mask plumbing.
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp
from flax import struct

Array = jax.Array


@struct.dataclass
class DenseBatch:
    """Dense design-matrix batch: margins are x @ w on the MXU."""

    x: Array  # [n, d]
    y: Array  # [n]
    offset: Array  # [n]
    weight: Array  # [n]

    @property
    def num_examples(self) -> int:
        return self.x.shape[-2]

    @property
    def dim(self) -> int:
        return self.x.shape[-1]

    def margins(self, w: Array) -> Array:
        """Raw margins x·w (no offset; callers add offset + normalization shift).

        Mixed precision: when ``x`` is stored narrower than ``w`` (bf16
        storage against an f32 solver state), the matmul runs with both MXU
        operands at storage width and accumulates at solver width — halves
        the HBM traffic of every objective pass, which is the bottleneck for
        large-n GLM solves, while coefficients/reductions stay f32."""
        if self.x.dtype != w.dtype:
            return jnp.matmul(self.x, w.astype(self.x.dtype),
                              preferred_element_type=w.dtype)
        return self.x @ w

    def rescale_weights(self, scale: Array) -> "DenseBatch":
        return self.replace(weight=self.weight * scale)


@struct.dataclass
class SparseBatch:
    """Row-padded sparse batch.

    ``indices[n, k]`` column ids, ``values[n, k]`` entries, padded with
    value 0.  ``dim`` is static (needed for gradient shapes).

    CONTRACT: within a row, non-padded indices must be unique and in
    [0, dim) — feature index maps guarantee this.  Duplicate indices would
    make ``hessian_diag`` (which squares per-slot values) disagree with the
    margin/gradient semantics; out-of-range indices clamp in gathers but drop
    in scatters.  The data layer validates on host at construction.
    """

    indices: Array  # [n, k] int32
    values: Array  # [n, k]
    y: Array  # [n]
    offset: Array  # [n]
    weight: Array  # [n]
    dim: int = struct.field(pytree_node=False)

    @property
    def num_examples(self) -> int:
        return self.values.shape[-2]

    def margins(self, w: Array) -> Array:
        # Gather + row-sum; transpose (for grad) is a segment-sum scatter-add,
        # which XLA derives from this expression.  Narrow-stored values are
        # widened in-register (mixed precision: bf16 HBM reads, f32 math).
        return jnp.sum(self.values.astype(w.dtype) * w[self.indices], axis=-1)

    def rescale_weights(self, scale: Array) -> "SparseBatch":
        return self.replace(weight=self.weight * scale)

    def to_dense(self) -> DenseBatch:
        """Materialize a dense design matrix (tests / tiny problems only)."""
        n, k = self.values.shape
        x = jnp.zeros((n, self.dim), self.values.dtype)
        rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, k))
        x = x.at[rows, self.indices].add(self.values)
        return DenseBatch(x=x, y=self.y, offset=self.offset, weight=self.weight)


Batch = Union[DenseBatch, SparseBatch]


def dense_batch(x, y, offset=None, weight=None, dtype=None) -> DenseBatch:
    """Convenience constructor with default offset 0 / weight 1."""
    x = jnp.asarray(x, dtype)
    y = jnp.asarray(y, x.dtype)
    n = x.shape[-2]
    offset = jnp.zeros((n,), x.dtype) if offset is None else jnp.asarray(offset, x.dtype)
    weight = jnp.ones((n,), x.dtype) if weight is None else jnp.asarray(weight, x.dtype)
    return DenseBatch(x=x, y=y, offset=offset, weight=weight)


def sparse_batch(indices, values, y, dim, offset=None, weight=None, dtype=None) -> SparseBatch:
    values = jnp.asarray(values, dtype)
    indices = jnp.asarray(indices, jnp.int32)
    y = jnp.asarray(y, values.dtype)
    n = values.shape[-2]
    offset = jnp.zeros((n,), values.dtype) if offset is None else jnp.asarray(offset, values.dtype)
    weight = jnp.ones((n,), values.dtype) if weight is None else jnp.asarray(weight, values.dtype)
    return SparseBatch(indices=indices, values=values, y=y, offset=offset, weight=weight, dim=dim)
