"""GAME scoring driver.

Reference: photon-client .../cli/game/scoring/GameScoringDriver.scala:39-263 —
load model -> read data -> GameTransformer -> write ScoringResultAvro ->
optional evaluation.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import List

import numpy as np

from photon_ml_tpu.data import avro as avro_io
from photon_ml_tpu.data.reader import read_game_data_avro
from photon_ml_tpu.data.schemas import SCORING_RESULT
from photon_ml_tpu.evaluation.evaluator import EvaluationSuite
from photon_ml_tpu.storage.model_io import load_model_bundle

logger = logging.getLogger("photon_ml_tpu.score")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-tpu-score",
                                description="Score data with a trained GAME model")
    p.add_argument("--data", nargs="+", required=True)
    p.add_argument("--model-dir", required=True,
                   help="directory produced by the training driver (contains "
                        "best/, *.idx, *.entities.json)")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--evaluators", default="")
    p.add_argument("--model-id", default="", help="stamped into score metadata")
    p.add_argument("--model-format", default="native",
                   choices=["native", "reference"],
                   help="'reference' imports a model saved by LinkedIn "
                        "Photon ML itself (ModelProcessingUtils on-disk "
                        "layout: model-metadata.json + fixed-effect/ + "
                        "random-effect/) — the migration path; index maps "
                        "are rebuilt from the stored feature names")
    p.add_argument("--predict-mean", action="store_true",
                   help="write inverse-link means instead of raw scores")
    p.add_argument("--input-date-range", default=None,
                   help="yyyyMMdd-yyyyMMdd daily-partition range (reference "
                        "IOUtils.getInputPathsWithinDateRange:113-153)")
    p.add_argument("--input-days-range", default=None,
                   help="START-END days ago (reference DaysRange.scala:28-48)")
    p.add_argument("--error-on-missing-date", action="store_true")
    p.add_argument("--input-columns", default="",
                   help="remap reserved input columns (see train driver)")
    p.add_argument("--log-data-and-model-stats", action="store_true",
                   help="log summaries of the model and scoring data "
                        "(reference GameScoringDriver logDataAndModelStats)")
    return p


def run(argv: List[str]) -> int:
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s %(message)s")
    args = build_parser().parse_args(argv)

    from photon_ml_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()

    from photon_ml_tpu.utils.dates import input_paths_within_date_range, resolve_range

    date_range = resolve_range(args.input_date_range, args.input_days_range)
    if date_range is not None:
        args.data = input_paths_within_date_range(
            args.data, date_range, args.error_on_missing_date)

    if args.model_format == "reference":
        from photon_ml_tpu.storage.model_io import import_reference_game_model

        try:
            model, task, index_maps, entity_indexes = \
                import_reference_game_model(args.model_dir)
        except (FileNotFoundError, KeyError, ValueError) as e:
            # ValueError covers json.JSONDecodeError (corrupt metadata)
            logger.error("--model-dir (reference format): %s", e)
            return 1
        logger.info("imported reference-format model: %d coordinate(s)",
                    len(model.models))
    else:
        from photon_ml_tpu.storage.model_io import ModelLoadError

        try:
            bundle = load_model_bundle(args.model_dir)
        except ModelLoadError as e:
            logger.error("--model-dir: %s", e)
            return 1
        model, task = bundle.model, bundle.task
        index_maps, entity_indexes = bundle.index_maps, bundle.entity_indexes
    id_tags = sorted(entity_indexes)
    from photon_ml_tpu.data.reader import parse_input_columns

    try:
        input_columns = parse_input_columns(args.input_columns)
    except ValueError as e:
        logger.error("%s", e)
        return 1
    data, _ = read_game_data_avro(args.data, index_maps, id_tag_names=id_tags,
                                  entity_indexes=entity_indexes,
                                  input_columns=input_columns)
    logger.info("scoring %d samples", data.num_samples)
    if args.log_data_and_model_stats:
        # reference logDataAndModelStats: toSummaryString dumps of the model
        # and the prepared dataset
        for cid, m in model.models.items():
            if hasattr(m, "slot_of"):  # either random-effect container
                width = (m.w_stack.shape[1] if hasattr(m, "w_stack")
                         else m.dim)
                logger.info("model %s: random effect %s, %d entities x %d "
                            "features", cid, m.random_effect_type,
                            m.num_entities, width)
            else:
                logger.info("model %s: fixed effect, %d features", cid,
                            len(m.coefficients.means))
        y = np.asarray(data.y, float)
        logger.info("data: %d samples, mean response %.6f, %d feature "
                    "shard(s)", data.num_samples, float(y.mean()),
                    len(data.features))
        for tag, ids in data.id_tags.items():
            known = int((np.asarray(ids) >= 0).sum())
            logger.info("data: id tag %s covers %d/%d samples", tag, known,
                        data.num_samples)

    from photon_ml_tpu.game.scoring import output_scores, raw_scores

    # One scoring pass; the inverse-link mean is a pointwise function of the
    # raw margin, so --predict-mean never re-scores (game/scoring.py — the
    # same composition the serving engine and GameTransformer use).
    raw = raw_scores(model, data)
    scores = output_scores(raw, task, predict_mean=args.predict_mean)

    os.makedirs(args.output_dir, exist_ok=True)
    out_path = os.path.join(args.output_dir, "scores.avro")
    meta = {"modelId": args.model_id} if args.model_id else None
    uids = data.uids if data.uids is not None else range(data.num_samples)
    records = (
        {"uid": (int(u) if isinstance(u, (int, np.integer)) else u),
         "predictionScore": float(scores[i]),
         "label": float(data.y[i]), "metadataMap": meta}
        for i, u in enumerate(uids)
    )
    n = avro_io.write_container(out_path, SCORING_RESULT, records)
    logger.info("wrote %d scores -> %s", n, out_path)

    if args.evaluators:
        # evaluators expect RAW margins regardless of the output format flag
        suite = EvaluationSuite.from_specs(args.evaluators.split(","))
        res = suite.evaluate(raw, data.y, data.weight, group_ids=data.id_tags)
        logger.info("metrics: %s", res.values)
        with open(os.path.join(args.output_dir, "metrics.json"), "w") as f:
            json.dump(res.values, f, indent=2)
    return 0


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()
