"""Feature-indexing driver: scan data, build per-shard index maps, save.

Reference: photon-client .../index/FeatureIndexingDriver.scala:41-320 (builds
partitioned PalDB stores; here the compact binary IndexMap format) and
NameAndTermFeatureBagsDriver (feature-bag scans).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
from typing import List

from photon_ml_tpu.data.index_map import (IndexMap, build_index_maps_from_avro,
                                           feature_key)

logger = logging.getLogger("photon_ml_tpu.index")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-tpu-index",
                                description="Build feature index maps from Avro data")
    p.add_argument("--data", nargs="*", default=[])
    p.add_argument("--feature-shards", required=True)
    p.add_argument("--feature-lists", default="",
                   help="shard=path[,shard=path...] of newline-delimited "
                        "'name<TAB>term' feature lists (the reference "
                        "NameAndTermFeatureBagsDriver's output format, "
                        "consumed by its FeatureIndexingDriver) — builds "
                        "each shard's map from the list instead of "
                        "scanning --data")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--no-intercept", action="store_true")
    p.add_argument("--format", choices=["idx", "store"], default="idx",
                   help="'idx' = compact dict-loaded format; 'store' = "
                        "mmap'd off-heap PHIDX002 store (PalDB equivalent, "
                        "for huge vocabularies)")
    return p


def run(argv: List[str]) -> int:
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s %(message)s")
    args = build_parser().parse_args(argv)
    shards = [s for s in args.feature_shards.split(",") if s]
    list_of = {}
    for kv in (args.feature_lists or "").split(","):
        if not kv:
            continue
        shard, _, path = kv.partition("=")
        if not path:
            logger.error("bad --feature-lists entry: %r", kv)
            return 1
        if shard not in shards:
            logger.error("--feature-lists names unknown shard %r "
                         "(--feature-shards has %s)", shard, shards)
            return 1
        list_of[shard] = path
    scan_shards = [s for s in shards if s not in list_of]
    if scan_shards and not args.data:
        logger.error("shards %s have no --feature-lists entry and no --data "
                     "to scan", scan_shards)
        return 1
    maps = {}
    if scan_shards:
        maps = build_index_maps_from_avro(args.data,
                                          {s: [] for s in scan_shards},
                                          add_intercept=not args.no_intercept)
    for shard, path in list_of.items():
        keys = {}
        with open(path) as f:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    continue
                name, _, term = line.partition("\t")
                keys.setdefault(feature_key(name, term), None)
        maps[shard] = IndexMap.build(keys, add_intercept=not args.no_intercept)
        logger.info("shard %s: %d features from list %s", shard,
                    maps[shard].size, path)
    os.makedirs(args.output_dir, exist_ok=True)
    for shard, m in maps.items():
        if args.format == "store":
            from photon_ml_tpu.data.native_index import build_store

            path = os.path.join(args.output_dir, f"{shard}.phidx")
            build_store(path, m)
        else:
            path = os.path.join(args.output_dir, f"{shard}.idx")
            m.save(path)
        logger.info("shard %s: %d features -> %s", shard, m.size, path)
    return 0


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()
