"""GAME training driver.

Reference: photon-client .../cli/game/training/GameTrainingDriver.scala:55-855 —
pipeline: feature maps -> data read -> validate -> normalization ->
reg-weight grid expansion -> GameEstimator.fit (warm-started across the grid)
-> optional hyperparameter tuning -> model selection -> save.

Usage:
  python -m photon_ml_tpu.cli.train \\
    --train-data /path/train.avro --validation-data /path/val.avro \\
    --feature-shards global,per_user \\
    --coordinate "name=fixed,feature.shard=global,reg.weights=0.1|1|10" \\
    --coordinate "name=user,random.effect.type=userId,feature.shard=per_user,reg.weights=1" \\
    --id-tags userId --task LOGISTIC_REGRESSION --evaluators auc \\
    --output-dir /path/out
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time
from typing import Dict, List

import numpy as np

from photon_ml_tpu.cli.config_grammar import expand_game_configs, parse_coordinate_spec
from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.data.reader import EntityIndex, read_game_data_avro
from photon_ml_tpu.data.validation import DataValidationType, validate_game_data
from photon_ml_tpu.evaluation.evaluator import EvaluationSuite
from photon_ml_tpu.game.estimator import GameEstimator
from photon_ml_tpu.storage.model_io import save_game_model
from photon_ml_tpu.types import TaskType

logger = logging.getLogger("photon_ml_tpu.train")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-tpu-train",
                                description="Train a GAME (GLMix) model on TPU")
    p.add_argument("--train-data", nargs="+", required=True,
                   help="Avro files/dirs of TrainingExampleAvro records")
    p.add_argument("--validation-data", nargs="*", default=[])
    p.add_argument("--input-date-range", default=None,
                   help="yyyyMMdd-yyyyMMdd: treat --train-data entries as base "
                        "dirs of daily <base>/yyyy/MM/dd partitions and read "
                        "the days in range (reference DateRange + "
                        "IOUtils.getInputPathsWithinDateRange:113-153)")
    p.add_argument("--input-days-range", default=None,
                   help="START-END in days ago, e.g. 90-1 (reference "
                        "DaysRange.scala:28-48); mutually exclusive with "
                        "--input-date-range")
    p.add_argument("--error-on-missing-date", action="store_true",
                   help="fail if any day in range has no data dir")
    p.add_argument("--input-columns", default="",
                   help="remap reserved input columns, e.g. "
                        "'response=clicked,weight=sampleWeight' (reference "
                        "InputColumnsNames: uid,response,offset,weight,"
                        "metadataMap,features)")
    p.add_argument("--feature-shards", required=True,
                   help="comma-separated feature shard names")
    p.add_argument("--coordinate", action="append", required=True, dest="coordinates",
                   help="coordinate spec (repeatable; see config grammar)")
    p.add_argument("--id-tags", default="", help="comma-separated id tag columns")
    p.add_argument("--task", default="LOGISTIC_REGRESSION",
                   choices=[t.name for t in TaskType if t != TaskType.NONE])
    p.add_argument("--evaluators", default="",
                   help="comma-separated evaluator specs (first = primary)")
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--index-map-dir", default=None,
                   help="load prebuilt index maps instead of scanning data")
    p.add_argument("--no-intercept", action="store_true")
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.name for v in DataValidationType])
    p.add_argument("--stream", action="store_true",
                   help="out-of-core streaming ingest (photonstream): decode "
                        "Avro chunks on a bounded background pool and "
                        "assemble design matrices ON DEVICE in fixed-shape "
                        "double-buffered batches — peak host memory stays "
                        "bounded by the pipeline window instead of the "
                        "dataset size; coefficients match the eager reader "
                        "bitwise")
    p.add_argument("--stream-batch-rows", type=int, default=4096,
                   help="device-feed batch rows (power of two; the one "
                        "upload shape the stream compiles)")
    p.add_argument("--stream-workers", type=int, default=2,
                   help="background decode threads")
    p.add_argument("--stream-on-error", default="raise",
                   choices=["raise", "skip"],
                   help="malformed-chunk policy: 'raise' fails the job at "
                        "the first corrupt/torn chunk; 'skip' keeps going — "
                        "lost rows stay allocated with weight 0 (inert) and "
                        "are counted in stream_chunk_errors_total / "
                        "stream_skipped_rows_total, never a silent short "
                        "epoch")
    p.add_argument("--sparse-threshold", type=int, default=0,
                   help="shards with >= this many features load as row-padded "
                        "sparse layouts (0 = always dense); the huge-vocabulary "
                        "path (reference scale story, SURVEY §2.7)")
    p.add_argument("--normalization", default="NONE",
                   choices=["NONE", "SCALE_WITH_MAX_MAGNITUDE",
                            "SCALE_WITH_STANDARD_DEVIATION", "STANDARDIZATION"],
                   help="feature normalization built from training stats "
                        "(reference NormalizationType.scala:42); models are "
                        "saved in original space")
    p.add_argument("--tuning-iterations", type=int, default=0,
                   help="GP hyperparameter tuning iterations (0 = off)")
    p.add_argument("--tuning-mode", default="bayesian", choices=["bayesian", "random"])
    p.add_argument("--tuner", default="BUILTIN",
                   help="DUMMY (no-op), BUILTIN, or module.path:ClassName "
                        "loaded reflectively (reference "
                        "HyperparameterTunerFactory.scala:20-48)")
    p.add_argument("--tuning-config", default=None,
                   help="JSON file in the reference HyperparameterSerialization "
                        "format ({tuning_mode, variables:{name:{transform,min,"
                        "max}}}); overrides --tuning-mode and the default L2 "
                        "search ranges (dims in unlocked-coordinate order)")
    p.add_argument("--tuning-priors", default=None,
                   help="JSON file of prior observations ({records:[{param:"
                        "value,...,evaluationValue:v}]}) seeded into the "
                        "search (reference priorFromJson)")
    p.add_argument("--tuning-shrink-radius", type=float, default=None,
                   help="with --tuning-priors: shrink the search domain to a "
                        "box of this radius (in rescaled [0,1] space) around "
                        "the GP-predicted best prior point (reference "
                        "ShrinkSearchRange.getBounds:40-100)")
    p.add_argument("--model-save-format", default="avro",
                   choices=["avro", "columnar"],
                   help="'avro' (default): name-keyed NTV triples, index-map-"
                        "independent and reference-portable; 'columnar': raw "
                        "coefficient arrays bound to this run's index maps — "
                        "seconds instead of minutes at 1e7+ features")
    p.add_argument("--model-output-mode", default="BEST",
                   choices=["NONE", "BEST", "EXPLICIT", "TUNED", "ALL"],
                   help="which trained models to save (reference "
                        "ModelOutputMode.scala: NONE = logs only; BEST = best "
                        "only; EXPLICIT = best + the reg-weight grid models; "
                        "TUNED = best + tuner-explored models; ALL = best + "
                        "everything)")
    p.add_argument("--output-models-limit", type=int, default=None,
                   help="cap on the number of extra models saved under models/ "
                        "(reference outputFilesLimit)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--model-input-dir", default=None,
                   help="existing model dir for warm start "
                        "(reference GameTrainingDriver modelInputDirectory)")
    p.add_argument("--model-input-format", default="native",
                   choices=["native", "reference"],
                   help="'reference' warm-starts from a model saved by "
                        "LinkedIn Photon ML itself (ModelProcessingUtils "
                        "layout; coordinate names must match this run's "
                        "--coordinate names) — the migration path")
    p.add_argument("--lock-coordinates", default="",
                   help="comma-separated coordinate ids kept from the input "
                        "model and only re-scored (partial retraining, "
                        "reference partialRetrainLockedCoordinates)")
    p.add_argument("--export-reference-model", default=None,
                   help="ALSO write the best model in the reference's "
                        "ModelProcessingUtils on-disk layout to this dir so "
                        "Spark-side Photon ML can load it (bidirectional "
                        "migration)")
    p.add_argument("--fused", default="auto", choices=["auto", "on", "off"],
                   help="descent engine: 'auto' (default) runs each fit as "
                        "ONE jitted program — validated fits included "
                        "(held-out scoring + per-update losses fused into "
                        "the scanned program, FusedSweep.run_validated) — "
                        "whenever no per-update host work (checkpoints, "
                        "locked coordinates, resume) is configured; 'off' "
                        "forces the host-paced CoordinateDescent (per-update "
                        "spans + history); 'on' requires the fused path and "
                        "errors where it cannot run")
    p.add_argument("--mesh", default=None,
                   help="device mesh spec 'data=4,entity=2,feature=1' — axes "
                        "default to 1, 'data' defaults to the remaining "
                        "devices; omit for single-device training")
    p.add_argument("--event-listener", action="append", default=[], dest="event_listeners",
                   help="'module.path:ClassName' lifecycle EventListener (repeatable)")
    p.add_argument("--checkpoint-dir", default=None,
                   help="flush descent state after every coordinate update and "
                        "auto-resume from it if present (preemption recovery; "
                        "mid-job checkpointing the reference lacks, SURVEY §5)")
    p.add_argument("--trace-out", default=None,
                   help="enable the photonscope tracer and write the Chrome "
                        "trace JSON (Perfetto-loadable; per-(iteration, "
                        "coordinate) descent spans with nested solve/score/"
                        "validate children) here at exit")
    p.add_argument("--trace-buffer", type=int, default=16384,
                   help="with --trace-out: tracer ring-buffer capacity "
                        "(newest spans win)")
    p.add_argument("--metrics-out", default=None,
                   help="write the unified metrics registry snapshot "
                        "(descent update counters/timings, compile + "
                        "transfer accounting) as JSON here at exit")
    return p


def run(argv: List[str]) -> int:
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s %(message)s")
    args = build_parser().parse_args(argv)

    from photon_ml_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    if args.trace_out:
        from photon_ml_tpu import obs

        obs.enable_tracing(capacity=args.trace_buffer)
        logger.info("tracing enabled (ring capacity %d)", args.trace_buffer)
    t_start = time.time()
    task = TaskType[args.task]

    # Job log next to the outputs + lifecycle events
    # (reference PhotonLogger @ GameTrainingDriver.scala:840-841; EventEmitter).
    from photon_ml_tpu.utils import EventEmitter, PhotonLogger

    os.makedirs(args.output_dir, exist_ok=True)
    # handler on the PACKAGE logger: descent/coordinate/etc records propagate
    # up the 'photon_ml_tpu.*' hierarchy into the job log
    job_log = PhotonLogger(os.path.join(args.output_dir, "log-message.txt"),
                           name="photon_ml_tpu")
    emitter = EventEmitter()
    for spec in args.event_listeners:
        emitter.register(spec)
    emitter.emit("training_start", task=args.task, output_dir=args.output_dir)
    try:
        return _run(args, task, t_start, emitter)
    finally:
        emitter.close_listeners()
        job_log.close()
        if args.trace_out:
            from photon_ml_tpu import obs

            obs.get_tracer().export_chrome_trace(args.trace_out)
            logger.info("trace -> %s", args.trace_out)
        if args.metrics_out:
            from photon_ml_tpu import obs

            obs.get_registry().export(args.metrics_out)
            logger.info("metrics -> %s", args.metrics_out)


def _run(args, task, t_start, emitter) -> int:
    from photon_ml_tpu.game.config import FixedEffectConfig
    from photon_ml_tpu.utils.dates import input_paths_within_date_range, resolve_range

    date_range = resolve_range(args.input_date_range, args.input_days_range)
    if date_range is not None:
        args.train_data = input_paths_within_date_range(
            args.train_data, date_range, args.error_on_missing_date)
        logging.getLogger(__name__).info(
            "date range %s -> %d daily input dirs", date_range, len(args.train_data))

    shards = [s for s in args.feature_shards.split(",") if s]
    id_tags = [s for s in args.id_tags.split(",") if s]
    try:
        specs = [parse_coordinate_spec(s) for s in args.coordinates]
    except ValueError as e:
        logger.error("--coordinate: %s", e)
        return 1

    # per-entity L2 multiplier files: validate and parse NOW — a bad path or
    # value must fail before hours of data loading (same early-failure rule
    # as the tuner resolution above)
    mult_by_spec = {}
    for i, spec in enumerate(specs):
        if spec.per_entity_l2_file is None:
            continue
        try:
            with open(spec.per_entity_l2_file) as f:
                raw = json.load(f)
            if not isinstance(raw, dict):
                raise ValueError(
                    f"expected a JSON object of entity -> multiplier, got "
                    f"{type(raw).__name__}")
            parsed = {}
            for name, m in raw.items():
                m = float(m)
                if not (m >= 0.0) or not np.isfinite(m):
                    raise ValueError(
                        f"entity {name!r}: multiplier {m} must be finite "
                        "and >= 0 (negative L2 is unbounded)")
                parsed[str(name)] = m
            mult_by_spec[i] = parsed
        except (OSError, ValueError, TypeError, json.JSONDecodeError) as e:
            logger.error("coordinate %s per-entity multipliers (%s): %s",
                         spec.name, spec.per_entity_l2_file, e)
            return 1

    # constraint files (reference constraint-string grammar): parse + shape-
    # check NOW; name->index resolution waits for the index maps
    constraint_entries_by_spec = {}
    for i, spec in enumerate(specs):
        if spec.constraints_file is None:
            continue
        try:
            with open(spec.constraints_file) as f:
                raw = json.load(f)
            if not isinstance(raw, list) or not all(
                    isinstance(e, dict) for e in raw):
                raise ValueError("expected a JSON array of constraint objects")
            constraint_entries_by_spec[i] = raw
        except (OSError, ValueError, TypeError, json.JSONDecodeError) as e:
            logger.error("coordinate %s constraints (%s): %s",
                         spec.name, spec.constraints_file, e)
            return 1

    # 1. index maps + training data.  Native loader (native/avro_loader.cpp):
    # columnar decode, no per-record Python objects — index maps and design
    # matrices both come from interned columnar buffers.  Python fallback:
    # decode ONCE, reuse the records for both steps.
    from photon_ml_tpu.data.avro import list_avro_files
    from photon_ml_tpu.data.index_map import (build_index_maps_from_avro,
                                              build_index_maps_from_records)
    from photon_ml_tpu.data.native_avro import schema_eligible

    from photon_ml_tpu.data.reader import parse_input_columns

    try:
        input_columns = parse_input_columns(args.input_columns)
    except ValueError as e:
        logger.error("%s", e)
        return 1
    if args.tuning_iterations > 0:
        # resolve the tuner NOW: a bad --tuner must fail before hours of
        # grid fitting, not after
        from photon_ml_tpu.tune.factory import tuner_factory

        try:
            tuner = tuner_factory(args.tuner)
        except ValueError as e:
            logger.error("%s", e)
            return 1

    if args.stream and "features" in input_columns and not args.index_map_dir:
        # the streaming index scan reads the default features column; a
        # remapped one needs prebuilt maps (eager record decode would defeat
        # out-of-core ingest)
        logger.error("--stream with a remapped features column requires "
                     "--index-map-dir")
        return 1

    # native columnar path only when EVERY file qualifies (and reads the
    # default reserved column names) — otherwise decode once through the
    # Python codec and reuse the records for both steps.  Streaming never
    # materializes the record list: index maps come from --index-map-dir or
    # the memory-bounded scan below.
    use_native = not args.stream and not input_columns and all(
        schema_eligible(f) for p in args.train_data
        for f in list_avro_files(p))
    train_records = None
    if not use_native and not args.stream:
        from photon_ml_tpu.data.avro import read_directory

        train_records = []
        for path in args.train_data:
            train_records.extend(read_directory(path))
    if args.index_map_dir:
        from photon_ml_tpu.data.index_map import load_index

        def _resolve(s):
            for ext in (".idx", ".phidx"):
                p = os.path.join(args.index_map_dir, s + ext)
                if os.path.exists(p):
                    return load_index(p)
            raise FileNotFoundError(f"no index map for shard {s!r} in {args.index_map_dir}")

        index_maps = {s: _resolve(s) for s in shards}
    elif train_records is None and args.stream:
        # the stream's malformed-block policy must govern this pre-pass too:
        # under --stream-on-error=skip a corrupt block costs its rows, not
        # the whole run (the eager scan would raise before the epoch's
        # policy ever applied)
        logger.info("building index maps from training data (streamed scan)")
        from photon_ml_tpu.stream.chunks import AvroStreamSource
        from photon_ml_tpu.stream.pipeline import ChunkPipeline

        def _stream_records():
            pipe = ChunkPipeline(AvroStreamSource(args.train_data),
                                 workers=args.stream_workers,
                                 on_error=args.stream_on_error)
            for _chunk, records, err in pipe:
                if err is None:
                    yield from records

        index_maps = build_index_maps_from_records(
            _stream_records(), shards, add_intercept=not args.no_intercept)
    elif train_records is None:
        logger.info("building index maps from training data (native scan)")
        index_maps = build_index_maps_from_avro(
            args.train_data, {s: [] for s in shards},
            add_intercept=not args.no_intercept)
    else:
        logger.info("building index maps from training data")
        index_maps = build_index_maps_from_records(
            train_records, shards, add_intercept=not args.no_intercept,
            features_col=input_columns.get("features", "features"))
    for s in shards:
        logger.info("shard %s: %d features", s, index_maps[s].size)

    sparse_shards = set()
    if args.sparse_threshold > 0:
        sparse_shards = {s for s in shards
                         if index_maps[s].size >= args.sparse_threshold}
        # random-effect coordinates train from sparse shards directly
        # (compact observed-column buckets, bucket_by_entity_sparse) EXCEPT
        # the ONE combination the sparse path still refuses loudly — those
        # shards stay dense so the run succeeds.  (Round 4 closed the other
        # carve-outs: RANDOM projection, FULL variances, box constraints and
        # shift normalization all run on sparse shards now.)
        from photon_ml_tpu.types import VarianceComputationType

        needs_dense = {
            spec.template.feature_shard for spec in specs
            if not isinstance(spec.template, FixedEffectConfig)
            # variances under compaction + per-entity normalization
            # contexts are refused together (game/coordinate._bind_solver)
            and (spec.template.variance != VarianceComputationType.NONE
                 and args.normalization != "NONE")}
        forced_dense = sparse_shards & needs_dense
        if forced_dense:
            logger.warning("shards %s stay dense: variance-computing "
                           "random-effect coordinates under normalization "
                           "need dense shards", sorted(forced_dense))
            sparse_shards -= forced_dense
        if sparse_shards:
            logger.info("sparse shards: %s", sorted(sparse_shards))

    # 2. assemble GameData (columnar fast path inside when native is up;
    # --stream assembles design matrices on device from the chunk pipeline)
    if args.stream:
        if sparse_shards:
            logger.error("--stream does not support sparse shards yet "
                         "(ROADMAP item 5 follow-on); drop "
                         "--sparse-threshold or the --stream flag")
            return 1
        from photon_ml_tpu.stream import stream_game_data

        # per-tag reservoir caps so EntityStats accumulates the capped
        # selection in O(entities * cap); tags whose coordinates disagree on
        # the cap accumulate full row lists (any cap answerable later)
        active_caps = {}
        seen_caps: Dict[str, set] = {}
        for spec in specs:
            t = spec.template
            if isinstance(t, FixedEffectConfig):
                continue
            seen_caps.setdefault(t.random_effect_type, set()).add(t.active_cap)
        for tag, caps in seen_caps.items():
            if len(caps) == 1 and (cap := next(iter(caps))) is not None:
                active_caps[tag] = cap
        data, entity_indexes = stream_game_data(
            args.train_data, index_maps, id_tag_names=id_tags,
            input_columns=input_columns,
            batch_rows=args.stream_batch_rows,
            workers=args.stream_workers, on_error=args.stream_on_error,
            active_caps=active_caps, seed=args.seed,
            validate=args.data_validation != "VALIDATE_DISABLED")
    else:
        data, entity_indexes = read_game_data_avro(
            args.train_data, index_maps, id_tag_names=id_tags,
            records=train_records, sparse_shards=sparse_shards,
            input_columns=input_columns)
    del train_records
    logger.info("train: %d samples", data.num_samples)
    val_data = None
    if args.validation_data:
        val_data, _ = read_game_data_avro(args.validation_data, index_maps,
                                          id_tag_names=id_tags,
                                          entity_indexes=entity_indexes,
                                          sparse_shards=sparse_shards,
                                          input_columns=input_columns)
        logger.info("validation: %d samples", val_data.num_samples)
    from photon_ml_tpu.data.native_avro import clear_columnar_cache

    clear_columnar_cache()  # decoded columns are folded into GameData now

    # 3. validate (reference DataValidators)
    errors = validate_game_data(
        data, task, DataValidationType[args.data_validation],
        allow_zero_weight=args.stream and args.stream_on_error == "skip")
    if errors:
        for e in errors:
            logger.error("validation: %s", e)
        return 1

    # 4. normalization from training stats (reference GameTrainingDriver
    # :430-436 FeatureDataStatistics + NormalizationContext per shard)
    normalization = None
    feature_stats = {}
    if args.normalization != "NONE":
        import dataclasses as _dc

        import jax.numpy as jnp

        from photon_ml_tpu.core.normalization import (build_normalization,
                                                      compute_feature_stats)
        from photon_ml_tpu.types import NormalizationType

        kind = NormalizationType[args.normalization]
        # normalization applies to EVERY coordinate on the shard, random
        # effects included (reference NormalizationContextRDD via
        # GameEstimator.prepareNormalizationContextWrappers:646-680); sparse
        # shards compute their stats straight from the COO arrays.  Shift
        # normalization (STANDARDIZATION) under per-entity compaction is
        # SUPPORTED since round 4 (the context is projected per entity and
        # the per-lane intercept position absorbs the margin shift —
        # game/coordinate.py); the intercept id is auto-filled from the
        # index maps below.  The one remaining shift refusal: a
        # feature-SHARDED sparse fixed effect (ShardSparseObjective is
        # scaling-only — shifts would densify sparse margins).
        norm_shards = {spec.template.feature_shard for spec in specs}
        if kind == NormalizationType.STANDARDIZATION:
            for spec in specs:
                t = spec.template
                if (isinstance(t, FixedEffectConfig)
                        and getattr(t, "feature_sharded", False)
                        and t.feature_shard in sparse_shards):
                    logger.error(
                        "coordinate %s: STANDARDIZATION shifts are not "
                        "supported on a feature-sharded sparse fixed effect "
                        "(shifts densify sparse margins) — use a factor-only "
                        "normalization", spec.name)
                    return 1
        normalization = {}
        for s in sorted(norm_shards):
            ii = index_maps[s].intercept_index
            shard_data = data.features[s]
            if s in sparse_shards:
                from photon_ml_tpu.core.normalization import \
                    compute_feature_stats_sparse

                stats = compute_feature_stats_sparse(
                    shard_data.indices, shard_data.values, shard_data.dim,
                    weight=data.weight, intercept_index=ii)
            else:
                stats = compute_feature_stats(jnp.asarray(shard_data),
                                              jnp.asarray(data.weight),
                                              intercept_index=ii)
            normalization[s] = build_normalization(kind, stats)
            if s in sparse_shards:
                # a huge-vocabulary shard must not dump dim-length JSON
                # lists (or loop the avro summary over millions of columns)
                # — record OBSERVED columns only, with their ids
                nnz = np.asarray(stats.num_nonzeros)
                keep = np.nonzero(nnz > 0)[0]
                if ii is not None and ii not in keep:
                    keep = np.sort(np.append(keep, ii))
                feature_stats[s] = {
                    "indices": keep.tolist(),
                    "mean": np.asarray(stats.mean)[keep].tolist(),
                    "variance": np.asarray(stats.variance)[keep].tolist(),
                    "abs_max": np.asarray(stats.abs_max)[keep].tolist(),
                    "intercept_index": ii,
                }
            else:
                feature_stats[s] = {
                    "mean": np.asarray(stats.mean).tolist(),
                    "variance": np.asarray(stats.variance).tolist(),
                    "abs_max": np.asarray(stats.abs_max).tolist(),
                    "intercept_index": ii,
                }
        logger.info("normalization %s over %d shard(s)", kind.name, len(normalization))

    # per-entity L2 multipliers: entity NAMES in the JSON file resolve
    # through the entity index built from the data (beyond-reference
    # feature; RandomEffectOptimizationProblem.scala:42 only envisioned
    # per-entity lambdas)
    import dataclasses as _dc

    for i, spec in enumerate(specs):
        if i not in mult_by_spec:
            continue
        re_type = spec.template.random_effect_type
        eidx = entity_indexes.get(re_type)
        if eidx is None:
            logger.error("per-entity multipliers for %r need id tag %r in "
                         "--id-tags", spec.name, re_type)
            return 1
        mult = {}
        missing = 0
        for name, m in mult_by_spec[i].items():
            eid = eidx.get(name)
            if eid < 0:
                missing += 1
                continue
            mult[eid] = m
        if missing:
            logger.warning("coordinate %s: %d multiplier entities not in "
                           "training data (ignored)", spec.name, missing)
        specs[i] = _dc.replace(spec, template=_dc.replace(
            spec.template, per_entity_l2_multipliers=mult))
        logger.info("coordinate %s: per-entity L2 multipliers for %d "
                    "entities", spec.name, len(mult))

    # constraint resolution: reference grammar names/terms -> this run's
    # feature indices (GLMSuite.createConstraintFeatureMap semantics)
    for i, entries in constraint_entries_by_spec.items():
        spec = specs[i]
        from photon_ml_tpu.cli.config_grammar import resolve_constraints

        try:
            resolved = resolve_constraints(
                entries, index_maps[spec.template.feature_shard])
            # bound validation (lo < hi, not both infinite) fires in the
            # config's __post_init__ — keep it inside the CLI error contract
            specs[i] = _dc.replace(spec, template=_dc.replace(
                spec.template, constraints=resolved))
        except ValueError as e:
            logger.error("coordinate %s constraints: %s", spec.name, e)
            return 1
        logger.info("coordinate %s: box constraints on %d feature(s)",
                    spec.name, len(resolved))

    # 5. config grid (reference prepareGameOptConfigs) + fit
    configs = expand_game_configs(specs, task, args.coordinate_descent_iterations)
    if normalization:
        # shift-normalized solves need the intercept column id (conversion
        # between model and transformed space, NormalizationContext.scala);
        # random effects also need it for the RANDOM projector's intercept
        # pass-through — fill from the index map unless the user set it
        configs = [
            _dc.replace(cfg, coordinates={
                cid: (_dc.replace(c, intercept_index=index_maps[c.feature_shard].intercept_index)
                      if c.intercept_index is None else c)
                for cid, c in cfg.coordinates.items()})
            for cfg in configs
        ]
    logger.info("fitting %d configuration(s)", len(configs))
    suite = (EvaluationSuite.from_specs(args.evaluators.split(","))
             if args.evaluators else None)
    mesh = None
    if args.mesh:
        from photon_ml_tpu.parallel.mesh import make_mesh

        axes = {}
        for part in args.mesh.split(","):
            k, _, v = part.partition("=")
            try:
                size = int(v)
            except ValueError:
                size = 0
            if k.strip() not in ("data", "entity", "feature") or size < 1:
                raise SystemExit(f"bad --mesh fragment {part!r} "
                                 "(expected data=N,entity=N,feature=N, N >= 1)")
            axes[k.strip()] = size
        mesh = make_mesh(n_data=axes.get("data"),
                         n_entity=axes.get("entity", 1),
                         n_feature=axes.get("feature", 1))
        logger.info("device mesh: %s", dict(mesh.shape))
    est = GameEstimator(mesh=mesh, validation_suite=suite,
                        normalization=normalization,
                        fused={"auto": "auto", "on": True,
                               "off": False}[args.fused])

    # Warm start / partial retraining (reference GameTrainingDriver.scala:370-379
    # -> GameEstimator initialModel + partial retraining :106-112).
    initial_model = None
    locked = {c for c in args.lock_coordinates.split(",") if c} or None
    if locked:
        known = {cid for cfg in configs for cid in cfg.coordinates}
        bad = locked - known
        if bad:
            logger.error("--lock-coordinates %s not among configured coordinates %s",
                         sorted(bad), sorted(known))
            return 1
    if args.model_input_dir and args.model_input_format == "reference":
        # Warm start / partial retraining FROM a model the reference itself
        # saved (migration): stored (name, term) coefficients remap into THIS
        # run's index maps; imported coordinate ids must match the training
        # coordinate names for warm start to engage.
        from photon_ml_tpu.storage.model_io import import_reference_game_model

        shard_by_cid = {s.name: s.template.feature_shard for s in specs}
        try:
            # subset migration: only coordinates named in this run's
            # --coordinate specs import; others are skipped, not errors
            initial_model, loaded_task, _, entity_indexes = \
                import_reference_game_model(
                    args.model_input_dir, entity_indexes=entity_indexes,
                    index_maps=index_maps, shard_of=shard_by_cid,
                    only=set(shard_by_cid))
        except (KeyError, FileNotFoundError, ValueError) as e:
            logger.error("--model-input-dir (reference format): %s", e)
            return 1
        if loaded_task != task:
            logger.error("input model task %s != --task %s", loaded_task, task)
            return 1
        # The imported per-entity coefficients are keyed by the model's
        # randomEffectType; if a same-named training coordinate uses a
        # DIFFERENT id tag, entity ids would silently misalign — refuse.
        re_type_by_cid = {
            s.name: s.template.random_effect_type for s in specs
            if not isinstance(s.template, FixedEffectConfig)}
        for cid, m in initial_model.models.items():
            want = re_type_by_cid.get(cid)
            got = getattr(m, "random_effect_type", None)
            if want is not None and got is not None and want != got:
                logger.error(
                    "imported coordinate %r has randomEffectType %r but this "
                    "run's coordinate uses random.effect.type=%r — entity "
                    "ids would misalign", cid, got, want)
                return 1
        logger.info("imported reference-format warm-start model "
                    "(%d coordinates%s)", len(initial_model.models),
                    f", locked: {sorted(locked)}" if locked else "")
    elif args.model_input_dir:
        from photon_ml_tpu.storage.model_io import load_game_model

        # accept either the training output dir (contains best/) or a model
        # dir itself (contains metadata.json)
        mdir = args.model_input_dir
        if not os.path.exists(os.path.join(mdir, "metadata.json")):
            mdir = os.path.join(mdir, "best")
        if not os.path.exists(os.path.join(mdir, "metadata.json")):
            logger.error("--model-input-dir %s: no model found (missing metadata.json)",
                         args.model_input_dir)
            return 1
        initial_model, loaded_task = load_game_model(mdir, index_maps, entity_indexes)
        if loaded_task != task:
            logger.error("input model task %s != --task %s", loaded_task, task)
            return 1
        logger.info("warm start from %s (%d coordinates%s)", args.model_input_dir,
                    len(initial_model.models),
                    f", locked: {sorted(locked)}" if locked else "")
    elif locked:
        logger.error("--lock-coordinates requires --model-input-dir")
        return 1

    # Checkpoint/resume (storage/checkpoint.py): resume wins over
    # --model-input-dir because it includes everything that dir did plus the
    # mid-job progress.
    checkpoint_hook = None
    resume_cursor = None
    resume_best = None
    if args.checkpoint_dir:
        import hashlib

        from photon_ml_tpu.storage.checkpoint import (has_checkpoint,
                                                       load_checkpoint,
                                                       save_checkpoint)

        # Fingerprint of everything the positional cursor and best-model
        # tracking depend on: a rerun with ANY of these changed must NOT
        # silently resume (wrong grid indices, skipped-but-never-ran locked
        # updates, best-metric comparisons across different primaries, or a
        # cursor applied to different data).
        fp_src = json.dumps({"coordinates": args.coordinates, "task": args.task,
                             "per_entity_multipliers": {
                                 str(i): sorted(d.items())
                                 for i, d in mult_by_spec.items()},
                             "iterations": args.coordinate_descent_iterations,
                             "seed": args.seed,
                             "train_data": sorted(args.train_data),
                             "validation_data": sorted(args.validation_data),
                             "evaluators": args.evaluators,
                             "lock": args.lock_coordinates,
                             "model_input": args.model_input_dir,
                             "model_input_format": args.model_input_format,
                             "normalization": args.normalization,
                             "sparse_threshold": args.sparse_threshold,
                             "feature_shards": args.feature_shards,
                             "id_tags": args.id_tags,
                             "no_intercept": args.no_intercept,
                             "index_map_dir": args.index_map_dir}, sort_keys=True)
        fingerprint = hashlib.sha256(fp_src.encode()).hexdigest()[:16]

        # Discriminator is the POINTER, not an exception type: a present
        # pointer names an atomically-written version, so ANY load failure
        # there (missing files included) is external damage and must refuse
        # loudly rather than silently retrain from scratch.
        if has_checkpoint(args.checkpoint_dir):
            try:
                initial_model, ck_task, resume_cursor, resume_best = load_checkpoint(
                    args.checkpoint_dir, index_maps, entity_indexes)
            except Exception as e:
                logger.error(
                    "checkpoint in %s is unreadable (%s); clear the dir to "
                    "start fresh or restore it to resume", args.checkpoint_dir, e)
                return 1
            if ck_task != task:
                logger.error("checkpoint task %s != --task %s", ck_task, task)
                return 1
            saved_fp = resume_cursor.pop("fingerprint", None)
            if saved_fp != fingerprint:
                logger.error(
                    "checkpoint in %s was written by a DIFFERENT configuration "
                    "(fingerprint %s != %s); refusing to resume — clear the "
                    "checkpoint dir or rerun with the original flags",
                    args.checkpoint_dir, saved_fp, fingerprint)
                return 1
            logger.info("resuming from checkpoint %s at %s", args.checkpoint_dir,
                        resume_cursor)

        def checkpoint_hook(model, cursor, updated=None, best=None, best_changed=True):
            save_checkpoint(args.checkpoint_dir, model, index_maps, cursor,
                            entity_indexes, task, updated_coordinate=updated,
                            best=best, best_changed=best_changed,
                            fingerprint=fingerprint,
                            fmt=args.model_save_format)

    # Always fit the explicit reg-weight grid; tuning then explores FROM the
    # best grid point (reference: grid first, tuner after, :643-674).
    emitter.emit("fit_start", configs=len(configs))
    try:
        results = est.fit(data, configs, validation_data=val_data, seed=args.seed,
                          initial_model=initial_model, locked_coordinates=locked,
                          checkpoint_hook=checkpoint_hook, resume_cursor=resume_cursor,
                          resume_best=resume_best)
    except (ValueError, NotImplementedError) as e:
        # config-shaped refusals raised at coordinate build/bind time (e.g.
        # box constraints under shift normalization, normalization under a
        # RANDOM projector) get the same error contract as every other
        # config validation failure — with the traceback preserved in the log
        logger.exception("configuration rejected during fit: %s", e)
        return 1
    best = est.best(results)
    tuned_results = []
    if args.tuning_iterations > 0:
        if val_data is None or suite is None:
            logger.error("tuning requires --validation-data and --evaluators")
            return 1
        tuning_mode, search_domain, prior_obs = args.tuning_mode, None, None
        unlocked = [c for c in best.config.coordinates if c not in (locked or ())]
        if args.tuning_config:
            from photon_ml_tpu.tune.serialization import config_from_json

            with open(args.tuning_config) as f:
                mode_str, search_domain = config_from_json(f.read())
            tuning_mode = mode_str.lower()
        if args.tuning_priors:
            from photon_ml_tpu.tune.serialization import (game_prior_default,
                                                          prior_from_json)

            names = ([d.name for d in search_domain.dims] if search_domain
                     else [f"l2:{c}" for c in unlocked])
            defaults = game_prior_default(unlocked)
            defaults.update({n: "0.0" for n in names})
            with open(args.tuning_priors) as f:
                prior_obs = prior_from_json(f.read(), defaults, names)
        # tuners without a search domain (DUMMY and kin) skip the prep work
        tuner_uses_domain = getattr(tuner, "uses_search_domain", True)
        if args.tuning_shrink_radius is not None and not tuner_uses_domain:
            logger.info("skipping search-range shrink: tuner ignores the "
                        "search domain")
        elif args.tuning_shrink_radius is not None:
            if not prior_obs:
                logger.error("--tuning-shrink-radius needs --tuning-priors")
                return 1
            from photon_ml_tpu.tune.shrink import shrink_search_range

            if search_domain is None:
                from photon_ml_tpu.tune.game_tuning import default_l2_domain

                search_domain = default_l2_domain(unlocked)
            minimize = not suite.primary.larger_is_better
            search_domain = shrink_search_range(
                search_domain, prior_obs, radius=args.tuning_shrink_radius,
                minimize=minimize, seed=args.seed)
            logger.info("shrunk tuning domain: %s",
                        [(d.name, round(d.low, 6), round(d.high, 6))
                         for d in search_domain.dims])

        _tuned, _search, tuned_results = tuner.tune(
            est, best.config, data, val_data,
            n_iterations=args.tuning_iterations,
            mode=tuning_mode, seed=args.seed,
            initial_model=initial_model,
            locked_coordinates=locked,
            search_domain=search_domain,
            prior_observations=prior_obs)
        if tuned_results:
            best = est.best(results + tuned_results)

    if best.evaluation is not None:
        logger.info("best model validation: %s", best.evaluation.values)

    # 6. save (reference saveModelToHDFS / ModelProcessingUtils /
    # selectModels:683-701 — output mode picks which extra models go under
    # models/<i>/ alongside best/)
    os.makedirs(args.output_dir, exist_ok=True)
    extra_models = {
        "NONE": [], "BEST": [],
        "EXPLICIT": results,
        "TUNED": tuned_results,
        "ALL": results + tuned_results,
    }[args.model_output_mode]
    if args.output_models_limit is not None:
        extra_models = extra_models[: args.output_models_limit]

    def _config_spec(cfg):
        """Per-coordinate optimization spec (reference
        IOUtils.writeOptimizationConfigToHDFS:195)."""
        spec = {}
        for cid, c in cfg.coordinates.items():
            spec[cid] = {"l1": c.reg.l1, "l2": c.reg.l2,
                         "optimizer": c.optimizer.name}
        return spec

    if args.export_reference_model:
        # independent of --model-output-mode: an explicitly requested
        # Spark-consumable artifact is written even under NONE
        from photon_ml_tpu.storage.model_io import export_reference_game_model

        export_reference_game_model(best.model, args.export_reference_model,
                                    index_maps, entity_indexes, task)
        logger.info("exported best model in reference layout -> %s",
                    args.export_reference_model)
    if args.model_output_mode != "NONE":
        save_game_model(best.model, os.path.join(args.output_dir, "best"),
                        index_maps, entity_indexes, task,
                        fmt=args.model_save_format)
        with open(os.path.join(args.output_dir, "best",
                               "model-spec.json"), "w") as f:
            json.dump(_config_spec(best.config), f, indent=2)
        for i, res in enumerate(extra_models):
            mdir = os.path.join(args.output_dir, "models", str(i))
            save_game_model(res.model, mdir, index_maps, entity_indexes, task,
                            fmt=args.model_save_format)
            with open(os.path.join(mdir, "model-spec.json"), "w") as f:
                json.dump({"config": _config_spec(res.config),
                           "validation": res.evaluation.values
                           if res.evaluation else None}, f, indent=2)
        for s in shards:
            from photon_ml_tpu.data.native_index import StoreIndexMap

            ext = ".phidx" if isinstance(index_maps[s], StoreIndexMap) else ".idx"
            index_maps[s].save(os.path.join(args.output_dir, f"{s}{ext}"))
        for tag, eidx in entity_indexes.items():
            eidx.save(os.path.join(args.output_dir, f"{tag}.entities.json"))
    if feature_stats:
        # reference ModelProcessingUtils.writeBasicStatistics:516 — JSON for
        # humans plus the reference's FeatureSummarizationResultAvro records
        # (per-feature metric map) for tool compatibility
        with open(os.path.join(args.output_dir, "feature-stats.json"), "w") as f:
            json.dump(feature_stats, f)
        from photon_ml_tpu.data import avro as avro_io
        from photon_ml_tpu.data.schemas import FEATURE_SUMMARY

        for s, st in feature_stats.items():
            imap = index_maps[s]

            def records(st=st, imap=imap):
                # sparse shards carry an explicit observed-column id list;
                # dense shards are positionally indexed
                cols = st.get("indices") or range(len(st["mean"]))
                for pos, j in enumerate(cols):
                    name_term = imap.get_feature_name(int(j))
                    if name_term is None:
                        continue
                    name, term = name_term
                    yield {"name": name, "term": term, "metrics": {
                        "mean": st["mean"][pos],
                        "variance": st["variance"][pos],
                        "absMax": st["abs_max"][pos],
                    }}

            avro_io.write_container(
                os.path.join(args.output_dir, f"{s}.feature-summary.avro"),
                FEATURE_SUMMARY, records())
    summary = {
        "task": task.value,
        "train_samples": int(data.num_samples),
        "configs": len(configs),
        "validation": best.evaluation.values if best.evaluation else None,
        "seconds": round(time.time() - t_start, 2),
    }
    with open(os.path.join(args.output_dir, "training-summary.json"), "w") as f:
        json.dump(summary, f, indent=2)
    emitter.emit("training_end", seconds=summary["seconds"],
                 validation=summary["validation"])
    logger.info("done in %.1fs -> %s", summary["seconds"], args.output_dir)
    return 0


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()
