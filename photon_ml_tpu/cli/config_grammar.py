"""Coordinate-configuration mini-grammar for the CLI.

Reference: photon-client .../io/scopt/ScoptParserHelpers.scala:495 — parses
specs like
  "name=global,feature.shard=shardA,optimizer=LBFGS,tolerance=1e-7,
   max.iter=50,reg.weights=0.1|1|10"
  "name=per-user,random.effect.type=userId,feature.shard=shardB,
   active.data.lower.bound=2,reg.weights=1"
and io/CoordinateConfiguration.scala:164 ``expandOptimizationConfigurations``
(cartesian grid over per-coordinate reg weights).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Tuple

from photon_ml_tpu.core.regularization import Regularization, RegularizationType
from photon_ml_tpu.game.config import (
    CoordinateConfig,
    FixedEffectConfig,
    GameConfig,
    RandomEffectConfig,
)
from photon_ml_tpu.opt.types import SolverConfig
from photon_ml_tpu.types import (OptimizerType, ProjectorType, TaskType,
                                 VarianceComputationType)


@dataclasses.dataclass
class CoordinateSpec:
    """One parsed --coordinate flag: config template + reg-weight sweep."""

    name: str
    reg_weights: List[float]
    reg_type: RegularizationType
    alpha: float
    template: CoordinateConfig  # reg filled per grid point
    # path of a JSON file {entityName: l2Multiplier}; the train driver
    # translates names -> ids once the entity index exists
    per_entity_l2_file: "str | None" = None
    # path of a JSON constraint file (reference constraint-string grammar,
    # GLMSuite.createConstraintFeatureMap:193-232): a JSON array of
    # {"name": ..., "term": ..., "lowerBound": ..., "upperBound": ...};
    # the train driver resolves names -> indices once index maps exist
    constraints_file: "str | None" = None

    def with_weight(self, w: float) -> CoordinateConfig:
        reg = Regularization.from_context(self.reg_type, w, self.alpha)
        return dataclasses.replace(self.template, reg=reg)


def parse_coordinate_spec(spec: str) -> CoordinateSpec:
    kv: Dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad coordinate spec fragment {part!r} (expected key=value)")
        k, v = part.split("=", 1)
        kv[k.strip()] = v.strip()

    name = kv.pop("name", None)
    if not name:
        raise ValueError(f"coordinate spec missing name=: {spec!r}")
    shard = kv.pop("feature.shard", None)
    if not shard:
        raise ValueError(f"coordinate {name!r} missing feature.shard=")

    optimizer = OptimizerType[kv.pop("optimizer", "LBFGS").upper()]
    solver = SolverConfig(
        max_iters=int(kv.pop("max.iter", 100)),
        tolerance=float(kv.pop("tolerance", 1e-7)),
    )
    reg_type = RegularizationType[kv.pop("reg.type", "L2").upper()]
    variance = VarianceComputationType[kv.pop("variance.type", "NONE").upper()]
    storage_dtype = kv.pop("storage.dtype", None)  # e.g. bfloat16 (mixed precision)
    if storage_dtype is not None:
        # fail at parse time with the key name, like every other grammar key
        import ml_dtypes  # registers bfloat16/float8 etc. with numpy  # noqa: F401
        import numpy as _np

        try:
            itemsize = _np.dtype(storage_dtype).itemsize
        except TypeError as e:
            raise ValueError(
                f"coordinate {name!r}: storage.dtype={storage_dtype!r} is not "
                "a dtype (use e.g. bfloat16 or float16)") from e
        if itemsize >= 4:
            raise ValueError(
                f"coordinate {name!r}: storage.dtype={storage_dtype!r} is not "
                "narrower than the f32 compute dtype — mixed-precision "
                "storage only makes sense at 16 bits or less")
        try:
            # floating-ness probe that also covers ml_dtypes' custom types
            # (this numpy registers bfloat16 with kind 'V', so issubdtype
            # against np.floating would wrongly reject it)
            ml_dtypes.finfo(_np.dtype(storage_dtype))
        except ValueError:
            # int8/uint8/bool sail past the itemsize check but silently
            # truncate the design matrix when cast host-side
            raise ValueError(
                f"coordinate {name!r}: storage.dtype={storage_dtype!r} is not "
                "a floating dtype (use bfloat16 or float16)") from None
    alpha = float(kv.pop("reg.alpha", 0.5))
    weights = [float(w) for w in kv.pop("reg.weights", "0").split("|")]
    # constraint.space=transformed: reference-compat raw bounds on the
    # transformed-space iterate (TRON.scala:228) — see MIGRATION.md
    constraint_space = kv.pop("constraint.space", "original")

    re_type = kv.pop("random.effect.type", None)
    if re_type is not None:
        # projection keys (reference RandomEffectDataConfiguration projector +
        # featuresToSamplesRatio grammar, ScoptParserHelpers.scala:495)
        projector = ProjectorType[kv.pop("projector", "IDENTITY").upper()]
        template: CoordinateConfig = RandomEffectConfig(
            random_effect_type=re_type,
            feature_shard=shard,
            optimizer=optimizer,
            solver=solver,
            active_cap=(int(kv["active.data.upper.bound"])
                        if "active.data.upper.bound" in kv else None),
            min_active_samples=int(kv.pop("active.data.lower.bound", 1)),
            projector=projector,
            projected_dim=(int(kv["projected.dim"])
                           if "projected.dim" in kv else None),
            features_to_samples_ratio=(float(kv["features.to.samples.ratio"])
                                       if "features.to.samples.ratio" in kv else None),
            intercept_index=(int(kv["intercept.index"])
                             if "intercept.index" in kv else None),
            variance=variance,
            storage_dtype=storage_dtype,
            constraint_space=constraint_space,
        )
        per_entity_file = kv.pop("per.entity.l2.multipliers", None)
        for consumed in ("active.data.upper.bound", "projected.dim",
                         "features.to.samples.ratio", "intercept.index"):
            kv.pop(consumed, None)
    else:
        per_entity_file = None
        template = FixedEffectConfig(
            feature_shard=shard,
            optimizer=optimizer,
            solver=solver,
            down_sampling_rate=float(kv.pop("down.sampling.rate", 1.0)),
            variance=variance,
            storage_dtype=storage_dtype,
            # huge-vocabulary model parallelism; active only when the mesh
            # has a feature axis > 1 (--mesh feature=N)
            feature_sharded=(kv.pop("feature.sharded", "false").lower()
                             in ("true", "1", "yes")),
            constraint_space=constraint_space,
        )
    constraints_file = kv.pop("constraints", None)
    if constraints_file and constraints_file.startswith("@"):
        constraints_file = constraints_file[1:]
    if kv:
        raise ValueError(f"unknown coordinate spec keys for {name!r}: {sorted(kv)}")
    return CoordinateSpec(name=name, reg_weights=weights, reg_type=reg_type,
                          alpha=alpha, template=template,
                          per_entity_l2_file=per_entity_file,
                          constraints_file=constraints_file)


def expand_game_configs(specs: List[CoordinateSpec], task: TaskType,
                        num_outer_iterations: int) -> List[GameConfig]:
    """Cartesian grid over per-coordinate reg weights
    (reference GameTrainingDriver.prepareGameOptConfigs:624-633)."""
    grids = [[(s.name, s.with_weight(w)) for w in s.reg_weights] for s in specs]
    configs = []
    for combo in itertools.product(*grids):
        configs.append(GameConfig(
            task=task,
            coordinates=dict(combo),
            num_outer_iterations=num_outer_iterations,
        ))
    return configs


WILDCARD = "*"


def resolve_constraints(entries, index_map) -> Tuple[Tuple[int, float, float], ...]:
    """Resolve a reference-grammar constraint list against a feature index map.

    Scale note: wildcard entries materialize one (index, lo, hi) triple per
    matched feature in Python — fine through ~1e5-feature vocabularies, but
    at the 1e7+ store-backed scale an all-feature wildcard means 1e7 python
    tuples and per-index name lookups; use explicit per-feature entries (or
    no constraints) there.

    Reference semantics (GLMSuite.createConstraintFeatureMap:193-260):
    - every entry needs "name" and "term"; missing bounds default to ∓inf;
    - lo < hi, not both infinite;
    - name="*" requires term="*" and applies to ALL features except the
      intercept; it may not be combined with any other constraint;
    - term="*" applies to every term of that name;
    - overlapping constraints (same feature twice) are an error.
    """
    out: Dict[int, Tuple[float, float]] = {}

    def put(j: int, lo: float, hi: float) -> None:
        if j in out:
            name_term = index_map.get_feature_name(j)
            raise ValueError(
                f"overlapping constraints for feature {name_term} (index {j})")
        out[j] = (lo, hi)

    saw_all_wildcard = False
    for e in entries:
        if "name" not in e or "term" not in e:
            raise ValueError(
                f"constraint entry must carry both 'name' and 'term': {e!r}")
        name, term = str(e["name"]), str(e["term"])
        lo = float(e.get("lowerBound", float("-inf")))
        hi = float(e.get("upperBound", float("inf")))
        if name == WILDCARD:
            if term != WILDCARD:
                raise ValueError(
                    "wildcard in feature name alone is not supported: if the "
                    "name is a wildcard the term must be a wildcard too")
            if out or saw_all_wildcard:
                raise ValueError(
                    "an all-feature wildcard constraint cannot be combined "
                    "with any other constraint")
            saw_all_wildcard = True
            ii = index_map.intercept_index
            for j in range(index_map.size):
                if j != ii:
                    put(j, lo, hi)
        elif term == WILDCARD:
            if saw_all_wildcard:
                raise ValueError(
                    "an all-feature wildcard constraint cannot be combined "
                    "with any other constraint")
            matched = [j for j in range(index_map.size)
                       if (nt := index_map.get_feature_name(j)) is not None
                       and nt[0] == name]
            for j in matched:
                put(j, lo, hi)
        else:
            if saw_all_wildcard:
                raise ValueError(
                    "an all-feature wildcard constraint cannot be combined "
                    "with any other constraint")
            j = index_map.get_index(name, term)
            if j >= 0:
                put(j, lo, hi)
    return tuple((j, lo, hi) for j, (lo, hi) in sorted(out.items()))
