"""photonlearn driver — labeled JSON-lines in, refit reports out.

Photon ML reference counterpart: none.  The reference retrains random
effects offline and republishes stores; this driver closes the loop the
paper leaves open: it loads the SAME training output ``cli/serve.py``
serves, then streams fresh labeled examples through
``online.IncrementalTrainer`` — warm-started batched per-entity Newton
refits whose updated rows publish through ``serving.HotSwapper`` into the
live store AND append to the durable ``online.DeltaLog`` under one
``(generation, delta_version)`` identity.  A serving replica started with
``serve.py --delta-log DIR`` on the same directory converges to these
rows with no other coordination.

Wire protocol (one JSON object per line on stdin / ``--examples`` file):

  example   the serving request format plus a label:
            {"uid": 7, "features": [["f0", 0.3], ...],
             "ids": {"userId": "user3"}, "offset": 0.0,
             "label": 1.0, "weight": 2.0}
            ("response" is accepted for "label" — the TrainingExampleAvro
            field name — and weight defaults to 1)
  flush     a blank line — refit the buffered mini-batch now (otherwise
            batches flush at ``--batch-size`` and at EOF)

Each flushed batch emits ONE report line on stdout
(``RefitReport.to_json``): entities refit, rows solved, publish identity
range, solve/publish timings.  ``--format avro`` reads
TrainingExampleAvro container files (``data/avro.read_container``)
instead of JSON lines — the batch pipeline's own output format, so
yesterday's scoring traffic can be replayed as today's fresh examples.

Restart safety: the delta log is opened BEFORE the coefficient store is
built, and the store's generation counter is advanced past the newest
logged generation (``advance_generation_floor``) — a restarted trainer
resumes with a strictly newer identity instead of colliding with rows it
logged in its previous life.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import IO, Iterator, List, Optional

from photon_ml_tpu.storage.model_io import ModelLoadError

logger = logging.getLogger("photon_ml_tpu.learn")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-tpu-learn",
                                description="Incremental per-entity refit "
                                            "of a trained GAME model from "
                                            "streamed labeled examples")
    p.add_argument("--model-dir", required=True,
                   help="training output dir (the same one serve.py loads)")
    p.add_argument("--examples", default="-",
                   help="labeled examples: JSON-lines file ('-' = stdin) "
                        "or an Avro container with --format avro")
    p.add_argument("--format", choices=("json", "avro"), default="json",
                   help="examples input format (avro = TrainingExampleAvro "
                        "container, the batch pipeline's own output)")
    p.add_argument("--batch-size", type=int, default=64,
                   help="mini-batch size: buffered examples refit together "
                        "when this many accumulate (blank line / EOF also "
                        "flush)")
    p.add_argument("--coordinates", default="",
                   help="comma list of random-effect coordinates to refit "
                        "(default: every SoA-eligible one)")
    p.add_argument("--l2", type=float, default=1.0,
                   help="per-entity ridge strength for the refits")
    p.add_argument("--max-iters", type=int, default=20,
                   help="Newton iteration cap per refit")
    p.add_argument("--min-rows", type=int, default=1,
                   help="entities with fewer fresh rows in a batch wait "
                        "for more data instead of refitting on noise")
    p.add_argument("--delta-log", default="",
                   help="durable delta log directory (online/delta_log.py); "
                        "this process OWNS it: every published row appends "
                        "here and hot swaps compact it.  Empty = publish "
                        "in-process only (nothing for a replica to follow)")
    p.add_argument("--fsync", choices=("always", "rotate", "never"),
                   default="always",
                   help="delta-log durability: fsync every append, only at "
                        "segment rotation, or never (test only)")
    p.add_argument("--warm", action="store_true",
                   help="AOT-warm the scoring bucket ladder too (only "
                        "useful when this process also answers scores)")
    p.add_argument("--repl-listen", default="",
                   help="host:port for the photonrepl log server "
                        "(online/replication): replicas subscribe here for "
                        "snapshot bootstrap + live delta shipping instead "
                        "of sharing the --delta-log directory.  Requires "
                        "--delta-log.  Port 0 = ephemeral (logged)")
    p.add_argument("--auth-token", default=None,
                   help="shared secret replication subscribers must "
                        "present (constant-time compare; one error frame, "
                        "then close).  Default: $PHOTON_AUTH_TOKEN")
    p.add_argument("--metrics-json", default="",
                   help="write the final metrics snapshot here at exit")
    p.add_argument("--trace", action="store_true",
                   help="enable the photonscope tracer (refit/publish "
                        "spans; publish waves mint photonpulse trace "
                        "contexts that ride the replication wire)")
    p.add_argument("--trace-buffer", type=int, default=8192,
                   help="tracer ring-buffer capacity (newest spans win)")
    p.add_argument("--trace-out", default="",
                   help="write the Chrome trace JSON here at exit "
                        "(implies --trace)")
    p.add_argument("--trace-label", default="owner",
                   help="photonpulse process label stamped on trace "
                        "exports and replication clock replies")
    p.add_argument("--flight-dir", default="",
                   help="photonpulse flight recorder spool: degradation "
                        "transitions dump the tracer ring here")
    p.add_argument("--flight-max-bytes", type=int, default=16 << 20,
                   help="on-disk byte bound for the flight spool")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="expose GET /metrics, /metrics.json and the "
                        "photonwatch /watchz federation pull on this "
                        "localhost port via a sidecar thread (0 = off)")
    p.add_argument("--watch", action="store_true",
                   help="photonwatch: enable span-aligned XLA device-time "
                        "attribution (xla_device_seconds{site=} + "
                        "device_us/host_us span attrs on solve.bucket)")
    p.add_argument("--slo", default="", metavar="FILE",
                   help="photonwatch SLO objectives (JSON list, "
                        "obs/watch/slo.py) evaluated against this "
                        "process's registry on a background thread")
    p.add_argument("--slo-interval", type=float, default=1.0,
                   help="seconds between --slo evaluation passes")
    return p


def _parse_hostport(value: str) -> tuple:
    host, sep, port = value.rpartition(":")
    if not sep or not host:
        raise ValueError(f"expected HOST:PORT, got {value!r}")
    return host, int(port)


def _avro_examples(path: str) -> Iterator[dict]:
    """TrainingExampleAvro records -> the trainer's wire-JSON dicts."""
    from photon_ml_tpu.data.avro import read_container

    for rec in read_container(path):
        yield {"uid": rec.get("uid"),
               "features": rec.get("features") or (),
               "ids": rec.get("metadataMap") or {},
               "offset": rec.get("offset") or 0.0,
               "label": rec.get("response", rec.get("label")),
               "weight": (1.0 if rec.get("weight") is None
                          else rec.get("weight"))}


def _learn_stream(trainer, lines: IO, out: IO, batch_size: int) -> int:
    """JSON-lines driver: buffer examples, refit on blank line /
    ``batch_size`` / EOF, emit one report line per flushed batch."""
    batch: List[dict] = []

    def flush() -> None:
        if not batch:
            return
        report = trainer.consume(batch)
        out.write(json.dumps(report.to_json()) + "\n")
        out.flush()
        batch.clear()

    for line in lines:
        line = line.strip()
        if not line:
            flush()
            continue
        try:
            obj = json.loads(line)
            if not isinstance(obj, dict):
                raise ValueError(f"expected a JSON object, got "
                                 f"{type(obj).__name__}")
        except ValueError as e:
            logger.error("bad example line: %s", e)
            out.write(json.dumps({"error": str(e)}) + "\n")
            out.flush()
            continue
        batch.append(obj)
        if len(batch) >= batch_size:
            flush()
    flush()
    return 0


def _learn_batches(trainer, examples: Iterator[dict], out: IO,
                   batch_size: int) -> int:
    """Avro driver: fixed-size mini-batches over a record iterator."""
    batch: List[dict] = []
    for obj in examples:
        batch.append(obj)
        if len(batch) >= batch_size:
            report = trainer.consume(batch)
            out.write(json.dumps(report.to_json()) + "\n")
            out.flush()
            batch.clear()
    if batch:
        report = trainer.consume(batch)
        out.write(json.dumps(report.to_json()) + "\n")
        out.flush()
    return 0


def run(argv: List[str]) -> int:
    logging.basicConfig(level=logging.INFO, stream=sys.stderr,
                        format="%(asctime)s %(name)s %(message)s")
    args = build_parser().parse_args(argv)
    if args.batch_size < 1:
        logger.error("--batch-size must be >= 1, got %d", args.batch_size)
        return 1

    from photon_ml_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()

    if args.trace or args.trace_out:
        from photon_ml_tpu import obs

        obs.enable_tracing(capacity=args.trace_buffer)
        logger.info("tracing enabled (ring capacity %d)", args.trace_buffer)

    from photon_ml_tpu.obs import pulse

    pulse.configure(args.trace_label)
    if args.flight_dir:
        pulse.set_flight(pulse.FlightRecorder(
            args.flight_dir, max_bytes=args.flight_max_bytes))
        logger.info("flight recorder spooling to %s (cap %d bytes)",
                    args.flight_dir, args.flight_max_bytes)

    from photon_ml_tpu.cli.serve import build_server
    from photon_ml_tpu.online.trainer import IncrementalTrainer, TrainerConfig

    delta_log = None
    if args.delta_log:
        from photon_ml_tpu.online.delta_log import DeltaLog
        from photon_ml_tpu.serving.coefficient_store import \
            advance_generation_floor

        delta_log = DeltaLog(args.delta_log, fsync=args.fsync)
        last = delta_log.last_identity()
        if last is not None:
            # restart safety: resume with a strictly newer generation than
            # anything already logged, BEFORE the store mints one
            advance_generation_floor(last[0] + 1)
            logger.info("delta log %s resumes past identity %s",
                        args.delta_log, last)

    coords = tuple(c.strip() for c in args.coordinates.split(",")
                   if c.strip()) or None
    try:
        engine, swapper = build_server(args.model_dir, warm=args.warm,
                                       delta_log=delta_log, log_owner=True)
        trainer = IncrementalTrainer(
            swapper,
            TrainerConfig(coordinates=coords, l2=args.l2,
                          max_iters=args.max_iters,
                          min_rows_per_entity=args.min_rows))
    except (ModelLoadError, ValueError) as e:
        logger.error("%s", e)
        return 1
    logger.info("learning on generation %d (version %r), task %s, "
                "coordinates %s", engine.store.generation,
                engine.store.version, engine.store.task.value,
                coords or "auto")

    # photonwatch: identity gauges always; attribution / SLO eval /
    # federation pull opt-in
    from photon_ml_tpu.obs.registry import export_build_info

    export_build_info(engine.metrics.registry, role="owner")
    if args.watch:
        from photon_ml_tpu.obs.watch import enable_attribution

        enable_attribution(engine.metrics.registry)
        logger.info("photonwatch: device-time attribution enabled")
    slo_thread = None
    if args.slo:
        from photon_ml_tpu.obs.watch import SLOEngine, SLOEvalThread, load_slos

        try:
            slos = load_slos(args.slo)
        except (OSError, ValueError) as e:
            logger.error("--slo: %s", e)
            return 1
        slo_thread = SLOEvalThread(SLOEngine(slos),
                                   lambda: engine.metrics.registry,
                                   interval_s=args.slo_interval).start()
        logger.info("photonwatch: evaluating %d SLO(s) every %.3fs",
                    len(slos), args.slo_interval)
    metrics_sidecar = None
    if args.metrics_port:
        from photon_ml_tpu.serving.frontend.metrics_http import \
            ThreadedMetricsEndpoint

        metrics_sidecar = ThreadedMetricsEndpoint(
            engine.metrics, port=args.metrics_port).start()
        logger.info("metrics scrape on http://127.0.0.1:%d/metrics "
                    "(+ /watchz)", metrics_sidecar.port)

    repl = None
    if args.repl_listen:
        if delta_log is None:
            logger.error("--repl-listen needs --delta-log (the log is "
                         "what gets replicated)")
            return 1
        import os as _os

        from photon_ml_tpu.online.replication import (ReplicationConfig,
                                                      attach_replication)

        try:
            host, port = _parse_hostport(args.repl_listen)
        except ValueError as e:
            logger.error("%s", e)
            return 1
        token = args.auth_token if args.auth_token is not None \
            else _os.environ.get("PHOTON_AUTH_TOKEN") or None
        repl = attach_replication(
            swapper, ReplicationConfig(host=host, port=port,
                                       auth_token=token),
            registry=engine.metrics.registry)
        logger.info("photonrepl serving the delta log on %s:%d%s", host,
                    repl.port, " (auth required)" if token else "")

    try:
        if args.format == "avro":
            if args.examples == "-":
                logger.error("--format avro needs --examples FILE "
                             "(containers are not streamable from stdin)")
                return 1
            rc = _learn_batches(trainer, _avro_examples(args.examples),
                                sys.stdout, args.batch_size)
        else:
            lines = sys.stdin if args.examples == "-" \
                else open(args.examples)
            try:
                rc = _learn_stream(trainer, lines, sys.stdout,
                                   args.batch_size)
            finally:
                if lines is not sys.stdin:
                    lines.close()
    finally:
        if slo_thread is not None:
            slo_thread.stop()
        if metrics_sidecar is not None:
            metrics_sidecar.stop()
        if repl is not None:
            repl.stop()
        if delta_log is not None:
            delta_log.close()
        if args.metrics_json:
            engine.metrics.export(args.metrics_json)
            logger.info("metrics -> %s", args.metrics_json)
        if args.trace_out:
            from photon_ml_tpu import obs

            obs.get_tracer().export_chrome_trace(args.trace_out)
            logger.info("trace -> %s", args.trace_out)
    return rc


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()
