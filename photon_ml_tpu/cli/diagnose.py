"""Model diagnostics driver: bootstrap CIs, learning curve, calibration,
feature importance, residual independence -> HTML + text report.

Reference: the legacy Driver's DIAGNOSED stage (photon-client Driver.scala:431,
photon-diagnostics **) — bootstrap training, fitting diagnostic,
Hosmer-Lemeshow, feature importance, Kendall-tau, rendered via the reporting
tree (diagnostics/reporting/**).  Operates on a trained model dir (the
training driver's output) plus the data it was trained on.

Usage:
  python -m photon_ml_tpu.cli.diagnose \\
    --data train.avro --holdout val.avro --model-dir out \\
    --coordinate fixed --output-dir out/diagnostics
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.core.batch import dense_batch
from photon_ml_tpu.core.losses import loss_for_task
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.data.index_map import load_index
from photon_ml_tpu.data.reader import EntityIndex, read_game_data_avro
from photon_ml_tpu.diagnostics import (bootstrap_training, expected_magnitude_importance,
                                       fitting_diagnostic, hosmer_lemeshow,
                                       kendall_tau_analysis, render_html, render_text,
                                       variance_importance)
from photon_ml_tpu.diagnostics.reporting import (Bars, Bullets, Document,
                                                 NumberedList, Plot,
                                                 Reference, Scatter, Table,
                                                 Text)
from photon_ml_tpu.models.glm import Coefficients, GLMModel
from photon_ml_tpu.opt.solve import make_solver
from photon_ml_tpu.storage.model_io import load_game_model
from photon_ml_tpu.types import TaskType

logger = logging.getLogger("photon_ml_tpu.diagnose")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-tpu-diagnose",
                                description="Diagnose a trained GAME model")
    p.add_argument("--data", nargs="+", required=True, help="training data (Avro)")
    p.add_argument("--holdout", nargs="*", default=[],
                   help="holdout data for the fitting diagnostic")
    p.add_argument("--model-dir", required=True,
                   help="training driver output dir (best/, *.idx, ...)")
    p.add_argument("--coordinate", default=None,
                   help="fixed-effect coordinate to diagnose (default: the only one)")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--bootstrap-replicates", type=int, default=16)
    p.add_argument("--l2", type=float, default=1.0,
                   help="L2 weight for the diagnostic re-trains")
    p.add_argument("--compare-l2", default="",
                   help="comma list of L2 weights: adds a regularization-"
                        "path comparison chapter (one nested subsection per "
                        "weight, like the legacy driver's per-lambda report "
                        "chapters)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--top-k", type=int, default=20)
    p.add_argument("--input-columns", default="",
                   help="remap reserved input columns (see train driver)")
    return p


def _load_dir(model_dir):
    index_maps, entity_indexes = {}, {}
    for name in os.listdir(model_dir):
        if name.endswith(".idx") or name.endswith(".phidx"):
            index_maps[name.rsplit(".", 1)[0]] = load_index(os.path.join(model_dir, name))
        elif name.endswith(".entities.json"):
            entity_indexes[name[: -len(".entities.json")]] = EntityIndex.load(
                os.path.join(model_dir, name))
    model, task = load_game_model(os.path.join(model_dir, "best"),
                                  index_maps, entity_indexes)
    return model, task, index_maps, entity_indexes


def run(argv: List[str]) -> int:
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s %(message)s")
    args = build_parser().parse_args(argv)

    # parse --compare-l2 BEFORE any model/data load (the repo's
    # early-failure rule: a bad flag value must not cost the whole read);
    # weights must be positive finite — the comparison plot is log-axis
    try:
        compare_weights = [float(v) for v in args.compare_l2.split(",") if v]
    except ValueError as e:
        logger.error("--compare-l2: %s", e)
        return 1
    if any(not (w > 0 and np.isfinite(w)) for w in compare_weights):
        logger.error("--compare-l2 weights must be positive finite (the "
                     "comparison plot is on a log axis); got %s",
                     args.compare_l2)
        return 1

    from photon_ml_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    model, task, index_maps, entity_indexes = _load_dir(args.model_dir)

    from photon_ml_tpu.models.game import (CompactRandomEffectModel,
                                           FixedEffectModel,
                                           RandomEffectModel)

    fixed = {cid: m for cid, m in model.models.items()
             if isinstance(m, FixedEffectModel)}
    random_effects = {cid: m for cid, m in model.models.items()
                      if isinstance(m, (RandomEffectModel,
                                        CompactRandomEffectModel))}
    if not fixed:
        logger.error("no fixed-effect coordinate in the model")
        return 1
    if args.coordinate:
        if args.coordinate not in model.models:
            logger.error("coordinate %r not found (have: %s)",
                         args.coordinate, sorted(model.models))
            return 1
        # restrict the per-coordinate chapters to the selection (full-model
        # calibration/residual chapters still cover the whole model)
        fixed = {k: v for k, v in fixed.items() if k == args.coordinate}
        random_effects = {k: v for k, v in random_effects.items()
                          if k == args.coordinate}
    loss = loss_for_task(task)

    id_tags = sorted(entity_indexes)
    from photon_ml_tpu.data.reader import parse_input_columns

    try:
        input_columns = parse_input_columns(args.input_columns)
    except ValueError as e:
        logger.error("%s", e)
        return 1
    data, _ = read_game_data_avro(args.data, index_maps, id_tag_names=id_tags,
                                  input_columns=input_columns,
                                  entity_indexes=entity_indexes)
    holdout_data = None
    if args.holdout:
        holdout_data, _ = read_game_data_avro(args.holdout, index_maps,
                                              input_columns=input_columns,
                                              id_tag_names=id_tags,
                                              entity_indexes=entity_indexes)
    logger.info("diagnosing %d fixed + %d random coordinate(s) on %d samples",
                len(fixed), len(random_effects), data.num_samples)

    obj = GLMObjective(loss=loss, reg=Regularization(l2=args.l2))
    solve = jax.jit(make_solver(obj))

    def train_fn(b):
        res = solve(jnp.zeros(b.dim, b.x.dtype), b)
        return GLMModel(coefficients=Coefficients(means=np.asarray(res.w)), task=task)

    def point_metric(m, b):
        z = np.asarray(m.coefficients.score(b.x)) + np.asarray(b.offset)
        w = np.asarray(b.weight)
        l = np.asarray(loss.loss(jnp.asarray(z), b.y))
        return float((w * l).sum() / max(w.sum(), 1e-12))

    doc = Document(f"Model diagnostics ({task.value})")
    summary: dict = {"task": task.value, "coordinates": {}}

    # per-coordinate raw scores on the training data — each coordinate is
    # diagnosed against the RESIDUAL of the others (the descent's partial
    # score, CoordinateDescent.scala:197-204), and calibration/residual
    # chapters use the FULL model
    coord_scores = {cid: np.asarray(m.score(data), np.float64)
                    for cid, m in model.models.items()}
    total_score = np.sum(list(coord_scores.values()), axis=0)
    base_offset = np.asarray(data.offset, np.float64)
    holdout_scores = ({cid: np.asarray(m.score(holdout_data), np.float64)
                       for cid, m in model.models.items()}
                      if holdout_data is not None else None)

    # ---- chapter: model summary (index + inventory) ----
    ch = doc.chapter("Model summary")
    inventory = []
    for mcid, m in model.models.items():
        if isinstance(m, FixedEffectModel):
            inventory.append(
                f"{mcid}: fixed effect on shard {m.feature_shard!r}, "
                f"{len(m.coefficients.means)} coefficients")
        else:
            width = (m.w_stack.shape[1] if hasattr(m, "w_stack") else m.dim)
            inventory.append(
                f"{mcid}: random effect per {m.random_effect_type!r} on shard "
                f"{m.feature_shard!r}, {m.num_entities} entities x "
                f"{width} coefficients")
    ch.section("Coordinates").add(Bullets(inventory))
    ch.section("Data").add(Bullets([
        f"training samples: {data.num_samples}",
        f"holdout samples: {holdout_data.num_samples if holdout_data else 0}",
        f"diagnostic re-train L2: {args.l2}",
    ]))

    # ---- per-fixed-coordinate chapters ----
    compare_results: dict = {}
    for cid, fe in fixed.items():
        shard = fe.feature_shard
        imap = index_maps[shard]
        residual = total_score - coord_scores[cid]
        batch = dense_batch(data.features[shard], data.y,
                            base_offset + residual, data.weight,
                            dtype=np.float64)

        def _label(j: int) -> str:
            nm = imap.get_feature_name(int(j))
            return f"{nm[0]}:{nm[1]}" if nm else str(j)

        names = [_label(j) for j in range(batch.dim)]
        if compare_weights:
            # per-weight solves run HERE so the dense float64 batch stays
            # transient (one coordinate's at a time); only the small tables
            # and losses are buffered for the comparison chapter below
            published = np.asarray(fe.coefficients.means, np.float64)
            per_weight = []
            for w in compare_weights:
                res = solve(jnp.zeros(batch.dim, batch.x.dtype), batch,
                            objective=obj.with_reg(Regularization(l2=w)))
                m = GLMModel(coefficients=Coefficients(
                    means=np.asarray(res.w)), task=task)
                wv = np.asarray(res.w, np.float64)
                move = np.abs(wv - published[: len(wv)])
                order = np.argsort(-move)[: min(args.top_k, len(move))]
                per_weight.append({
                    "w": w,
                    "rows": [[names[j], f"{wv[j]:.5g}",
                              f"{published[j]:.5g}", f"{move[j]:.5g}"]
                             for j in order],
                    "train_loss": point_metric(m, batch),
                    "norm": float(np.linalg.norm(wv)),
                })
            compare_results[cid] = per_weight
        ch = doc.chapter(f"Coordinate {cid!r} (fixed effect)",
                         label=f"coord:{cid}")
        cs: dict = {}

        # 1. bootstrap confidence intervals (BootstrapTraining.scala:29-181)
        report = bootstrap_training(
            train_fn, batch, num_replicates=args.bootstrap_replicates,
            metrics={"mean_loss": lambda m: point_metric(m, batch)},
            seed=args.seed)
        sec = ch.section(f"Bootstrap 95% coefficient intervals "
                         f"({args.bootstrap_replicates} replicates)")
        order = np.argsort(-np.abs(report.coefficient_means))[: args.top_k]
        sec.add(Table(["feature", "mean", "lo", "hi"],
                      [[names[j], f"{report.coefficient_means[j]:.5g}",
                        f"{report.coefficient_intervals[j][0]:.5g}",
                        f"{report.coefficient_intervals[j][1]:.5g}"]
                       for j in order]))
        sec.add(Plot("coefficient mean and 95% interval by |mean| rank",
                     list(range(len(order))),
                     {"mean": [float(report.coefficient_means[j]) for j in order],
                      "lo": [float(report.coefficient_intervals[j][0]) for j in order],
                      "hi": [float(report.coefficient_intervals[j][1]) for j in order]},
                     x_label="rank"))
        mean, std = report.metric_summary()["mean_loss"]
        sec.add(Text(f"bootstrap mean loss: {mean:.6g} ± {std:.3g}"))
        cs["bootstrap"] = {"replicates": report.num_replicates,
                           "mean_loss": [mean, std]}

        # 2. learning curve (FittingDiagnostic.scala:33-131)
        if holdout_data is not None:
            h_residual = np.sum([s for ocid, s in holdout_scores.items()
                                 if ocid != cid], axis=0) \
                if len(holdout_scores) > 1 else \
                np.zeros(holdout_data.num_samples, np.float64)
            hbatch = dense_batch(holdout_data.features[shard], holdout_data.y,
                                 np.asarray(holdout_data.offset, np.float64)
                                 + h_residual,
                                 holdout_data.weight, dtype=np.float64)
            fit = fitting_diagnostic(train_fn, {"mean_loss": point_metric},
                                     batch, hbatch, seed=args.seed)
            ch.section("Learning curve (train vs holdout)").add(
                Plot("mean loss vs training fraction", list(fit.fractions),
                     {"train": list(fit.train_metrics["mean_loss"]),
                      "holdout": list(fit.holdout_metrics["mean_loss"])},
                     x_label="fraction"))
            cs["fitting"] = {"fractions": fit.fractions.tolist(),
                             "train": fit.train_metrics["mean_loss"].tolist(),
                             "holdout": fit.holdout_metrics["mean_loss"].tolist()}

        # 3. feature importance (featureimportance/*)
        x_np = np.asarray(batch.x)
        em = expected_magnitude_importance(np.asarray(fe.coefficients.means),
                                           np.abs(x_np).mean(0), names, args.top_k)
        vi = variance_importance(np.asarray(fe.coefficients.means),
                                 x_np.var(0), names, args.top_k)
        sec = ch.section("Feature importance")
        sec.add(Bars("expected magnitude |w|*E|x|",
                     [n for n, _ in em.ranked], [v for _, v in em.ranked]))
        sec.add(Table(["feature", "importance"],
                      [[n, f"{v:.5g}"] for n, v in em.ranked]))
        sec.add(Bars("variance w^2*Var[x]",
                     [n for n, _ in vi.ranked], [v for _, v in vi.ranked]))
        sec.add(Table(["feature", "importance"],
                      [[n, f"{v:.5g}"] for n, v in vi.ranked]))
        summary["coordinates"][cid] = cs

    # ---- regularization-path comparison chapter (legacy Driver trains a
    # per-lambda path and its diagnostic report carries per-lambda chapters;
    # photon-diagnostics reporting/** nests them as sections) ----
    if compare_weights:
        ch = doc.chapter("Regularization path comparison", label="regpath")
        ch.section("Weights compared").add(NumberedList(
            [f"l2 = {w:g}" for w in compare_weights]))
        for cid, per_weight in compare_results.items():
            sec = ch.section(f"Coordinate {cid!r}")
            sec.add(Reference(f"coord:{cid}",
                              "full diagnostics for this coordinate"))
            for entry in per_weight:
                ss = sec.subsection(f"l2 = {entry['w']:g}")
                ss.add(Table(["feature", "w(l2)", "published", "|shift|"],
                             entry["rows"]))
                ss.add(Text(f"train mean loss: {entry['train_loss']:.6g}; "
                            f"coefficient norm: {entry['norm']:.5g}"))
            xs = [float(np.log10(w)) for w in compare_weights]
            sec.add(Plot("mean loss vs log10(l2)", xs,
                         {"train": [e["train_loss"] for e in per_weight]},
                         x_label="log10(l2)", y_label="mean loss"))
        summary["regularization_path"] = {
            "weights": compare_weights,
        }

    # ---- per-random-coordinate chapters ----
    for cid, re_model in random_effects.items():
        ch = doc.chapter(f"Coordinate {cid!r} (random effect)")
        # either container: the compact model's value rows are 0-padded, so
        # their norms equal the dense rows'
        stack = (re_model.w_stack if hasattr(re_model, "w_stack")
                 else re_model.values)
        norms = np.linalg.norm(np.asarray(stack, np.float64), axis=1)
        qs = np.quantile(norms, [0.0, 0.25, 0.5, 0.75, 1.0]) if len(norms) else [0] * 5
        ch.section("Per-entity coefficient norms").add(Table(
            ["entities", "min", "p25", "median", "p75", "max"],
            [[str(len(norms))] + [f"{q:.5g}" for q in qs]]))
        hist, edges = np.histogram(norms, bins=min(16, max(4, len(norms) // 4 or 4)))
        ch.sections[-1].add(Bars(
            "entity count by ||w|| bin",
            [f"[{edges[i]:.3g},{edges[i+1]:.3g})" for i in range(len(hist))],
            hist.tolist()))
        top = np.argsort(-norms)[: args.top_k]
        inv = {v: k for k, v in re_model.slot_of.items()}
        ch.section("Largest entities by ||w||").add(Table(
            ["entity", "||w||"],
            [[str(inv.get(int(j), int(j))), f"{norms[j]:.5g}"] for j in top]))
        summary["coordinates"][cid] = {
            "entities": int(len(norms)),
            "norm_quantiles": [float(q) for q in qs],
        }

    # ---- full-model chapters: calibration + residual independence ----
    margins = total_score + base_offset
    preds = np.asarray(loss.mean(jnp.asarray(margins)))
    y = np.asarray(data.y, np.float64)

    if task == TaskType.LOGISTIC_REGRESSION:
        try:
            hl = hosmer_lemeshow(preds, y, np.asarray(data.weight))
            sec = doc.chapter("Calibration (full model)").section("Hosmer-Lemeshow")
            sec.add(Text(f"chi2={hl.chi_square:.4f} df={hl.degrees_of_freedom} "
                         f"p={hl.p_value:.4g}"))
            sec.add(Table(["bin_lo", "bin_hi", "total", "obs+", "exp+"],
                          [[f"{hl.bin_edges[i]:.3f}", f"{hl.bin_edges[i+1]:.3f}",
                            f"{hl.totals[i]:.1f}", f"{hl.observed_pos[i]:.1f}",
                            f"{hl.expected_pos[i]:.1f}"]
                           for i in range(len(hl.totals))]))
            centers = [(hl.bin_edges[i] + hl.bin_edges[i + 1]) / 2
                       for i in range(len(hl.totals))]
            safe_tot = np.maximum(np.asarray(hl.totals), 1e-12)
            sec.add(Plot("observed vs expected positive rate per bin", centers,
                         {"observed": (np.asarray(hl.observed_pos) / safe_tot).tolist(),
                          "expected": (np.asarray(hl.expected_pos) / safe_tot).tolist()},
                         x_label="predicted probability bin"))
            summary["hosmer_lemeshow"] = {"chi_square": hl.chi_square,
                                          "df": hl.degrees_of_freedom,
                                          "p_value": hl.p_value}
        except ValueError as e:
            logger.warning("Hosmer-Lemeshow skipped: %s", e)

    kt = kendall_tau_analysis(preds, y, seed=args.seed)
    sec = doc.chapter("Residuals (full model)").section(
        "Kendall tau (prediction vs error)")
    sec.add(Text(kt.summary()))
    sub = np.random.default_rng(args.seed).permutation(len(preds))[:2000]
    sec.add(Scatter("prediction vs residual", preds[sub].tolist(),
                    (y - preds)[sub].tolist(),
                    x_label="prediction", y_label="residual"))
    summary["kendall_tau"] = {"tau": kt.tau, "p_value": kt.p_value}

    os.makedirs(args.output_dir, exist_ok=True)
    with open(os.path.join(args.output_dir, "report.html"), "w") as f:
        f.write(render_html(doc))
    with open(os.path.join(args.output_dir, "report.txt"), "w") as f:
        f.write(render_text(doc))
    with open(os.path.join(args.output_dir, "diagnostics.json"), "w") as f:
        json.dump(summary, f, indent=2)
    logger.info("report -> %s", os.path.join(args.output_dir, "report.html"))
    return 0


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()
