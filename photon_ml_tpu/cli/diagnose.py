"""Model diagnostics driver: bootstrap CIs, learning curve, calibration,
feature importance, residual independence -> HTML + text report.

Reference: the legacy Driver's DIAGNOSED stage (photon-client Driver.scala:431,
photon-diagnostics **) — bootstrap training, fitting diagnostic,
Hosmer-Lemeshow, feature importance, Kendall-tau, rendered via the reporting
tree (diagnostics/reporting/**).  Operates on a trained model dir (the
training driver's output) plus the data it was trained on.

Usage:
  python -m photon_ml_tpu.cli.diagnose \\
    --data train.avro --holdout val.avro --model-dir out \\
    --coordinate fixed --output-dir out/diagnostics
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.core.batch import DenseBatch, dense_batch
from photon_ml_tpu.core.losses import loss_for_task
from photon_ml_tpu.core.objective import GLMObjective
from photon_ml_tpu.core.regularization import Regularization
from photon_ml_tpu.data.index_map import load_index
from photon_ml_tpu.data.reader import EntityIndex, read_game_data_avro
from photon_ml_tpu.diagnostics import (bootstrap_training, expected_magnitude_importance,
                                       fitting_diagnostic, hosmer_lemeshow,
                                       kendall_tau_analysis, render_html, render_text,
                                       variance_importance)
from photon_ml_tpu.diagnostics.reporting import Chapter, Document, Plot, Table, Text
from photon_ml_tpu.models.glm import Coefficients, GLMModel
from photon_ml_tpu.opt.solve import make_solver
from photon_ml_tpu.storage.model_io import load_game_model
from photon_ml_tpu.types import TaskType

logger = logging.getLogger("photon_ml_tpu.diagnose")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-tpu-diagnose",
                                description="Diagnose a trained GAME model")
    p.add_argument("--data", nargs="+", required=True, help="training data (Avro)")
    p.add_argument("--holdout", nargs="*", default=[],
                   help="holdout data for the fitting diagnostic")
    p.add_argument("--model-dir", required=True,
                   help="training driver output dir (best/, *.idx, ...)")
    p.add_argument("--coordinate", default=None,
                   help="fixed-effect coordinate to diagnose (default: the only one)")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--bootstrap-replicates", type=int, default=16)
    p.add_argument("--l2", type=float, default=1.0,
                   help="L2 weight for the diagnostic re-trains")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--top-k", type=int, default=20)
    p.add_argument("--input-columns", default="",
                   help="remap reserved input columns (see train driver)")
    return p


def _load_dir(model_dir):
    index_maps, entity_indexes = {}, {}
    for name in os.listdir(model_dir):
        if name.endswith(".idx") or name.endswith(".phidx"):
            index_maps[name.rsplit(".", 1)[0]] = load_index(os.path.join(model_dir, name))
        elif name.endswith(".entities.json"):
            entity_indexes[name[: -len(".entities.json")]] = EntityIndex.load(
                os.path.join(model_dir, name))
    model, task = load_game_model(os.path.join(model_dir, "best"),
                                  index_maps, entity_indexes)
    return model, task, index_maps, entity_indexes


def _dense_batch(data, shard: str) -> DenseBatch:
    return dense_batch(data.features[shard], data.y, data.offset, data.weight,
                       dtype=np.float64)


def run(argv: List[str]) -> int:
    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s %(message)s")
    args = build_parser().parse_args(argv)

    from photon_ml_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    model, task, index_maps, entity_indexes = _load_dir(args.model_dir)

    from photon_ml_tpu.models.game import FixedEffectModel

    fixed = {cid: m for cid, m in model.models.items()
             if isinstance(m, FixedEffectModel)}
    if not fixed:
        logger.error("no fixed-effect coordinate in the model")
        return 1
    cid = args.coordinate or next(iter(fixed))
    if cid not in fixed:
        logger.error("coordinate %r not found (have: %s)", cid, sorted(fixed))
        return 1
    fe = fixed[cid]
    shard = fe.feature_shard
    imap = index_maps[shard]
    loss = loss_for_task(task)

    id_tags = sorted(entity_indexes)
    from photon_ml_tpu.data.reader import parse_input_columns

    try:
        input_columns = parse_input_columns(args.input_columns)
    except ValueError as e:
        logger.error("%s", e)
        return 1
    data, _ = read_game_data_avro(args.data, index_maps, id_tag_names=id_tags,
                                  input_columns=input_columns,
                                  entity_indexes=entity_indexes)
    batch = _dense_batch(data, shard)
    logger.info("diagnosing coordinate %r on %d samples", cid, data.num_samples)

    obj = GLMObjective(loss=loss, reg=Regularization(l2=args.l2))
    solve = jax.jit(make_solver(obj))

    def train_fn(b):
        res = solve(jnp.zeros(b.dim, b.x.dtype), b)
        return GLMModel(coefficients=Coefficients(means=np.asarray(res.w)), task=task)

    def point_metric(m, b):
        z = np.asarray(m.coefficients.score(b.x)) + np.asarray(b.offset)
        w = np.asarray(b.weight)
        l = np.asarray(loss.loss(jnp.asarray(z), b.y))
        return float((w * l).sum() / max(w.sum(), 1e-12))

    doc = Document(f"Diagnostics: coordinate {cid!r} ({task.value})")

    def _label(j: int) -> str:
        nm = imap.get_feature_name(int(j))
        return f"{nm[0]}:{nm[1]}" if nm else str(j)

    names = [_label(j) for j in range(batch.dim)]

    # 1. bootstrap confidence intervals (BootstrapTraining.scala:29-181)
    report = bootstrap_training(train_fn, batch, num_replicates=args.bootstrap_replicates,
                                metrics={"mean_loss": lambda m: point_metric(m, batch)},
                                seed=args.seed)
    ch = doc.chapter("Bootstrap")
    sec = ch.section(f"Coefficient {95.0:.0f}% intervals ({args.bootstrap_replicates} replicates)")
    rows = []
    order = np.argsort(-np.abs(report.coefficient_means))[: args.top_k]
    for j in order:
        lo, hi = report.coefficient_intervals[j]
        rows.append([names[j], f"{report.coefficient_means[j]:.5g}",
                     f"{lo:.5g}", f"{hi:.5g}"])
    sec.add(Table(["feature", "mean", "lo", "hi"], rows))
    mean, std = report.metric_summary()["mean_loss"]
    sec.add(Text(f"bootstrap mean loss: {mean:.6g} ± {std:.3g}"))

    # 2. learning curve (FittingDiagnostic.scala:33-131)
    fit_payload = None
    if args.holdout:
        holdout_data, _ = read_game_data_avro(args.holdout, index_maps,
                                              input_columns=input_columns,
                                              id_tag_names=id_tags,
                                              entity_indexes=entity_indexes)
        fit = fitting_diagnostic(train_fn, {"mean_loss": point_metric}, batch,
                                 _dense_batch(holdout_data, shard), seed=args.seed)
        sec = doc.chapter("Fitting").section("Learning curve (train vs holdout)")
        sec.add(Plot("mean loss vs training fraction", list(fit.fractions),
                     {"train": list(fit.train_metrics["mean_loss"]),
                      "holdout": list(fit.holdout_metrics["mean_loss"])},
                     x_label="fraction"))
        fit_payload = {"fractions": fit.fractions.tolist(),
                       "train": fit.train_metrics["mean_loss"].tolist(),
                       "holdout": fit.holdout_metrics["mean_loss"].tolist()}

    # predictions of the ACTUAL trained model for calibration/independence
    margins = np.asarray(fe.coefficients.score(batch.x)) + np.asarray(batch.offset)
    preds = np.asarray(loss.mean(jnp.asarray(margins)))
    y = np.asarray(batch.y)

    # 3. calibration (logistic only; HosmerLemeshowDiagnostic)
    hl_payload = None
    if task == TaskType.LOGISTIC_REGRESSION:
        try:
            hl = hosmer_lemeshow(preds, y, np.asarray(batch.weight))
            sec = doc.chapter("Calibration").section("Hosmer-Lemeshow")
            sec.add(Text(f"chi2={hl.chi_square:.4f} df={hl.degrees_of_freedom} "
                         f"p={hl.p_value:.4g}"))
            sec.add(Table(["bin_lo", "bin_hi", "total", "obs+", "exp+"],
                          [[f"{hl.bin_edges[i]:.3f}", f"{hl.bin_edges[i+1]:.3f}",
                            f"{hl.totals[i]:.1f}", f"{hl.observed_pos[i]:.1f}",
                            f"{hl.expected_pos[i]:.1f}"]
                           for i in range(len(hl.totals))]))
            hl_payload = {"chi_square": hl.chi_square, "df": hl.degrees_of_freedom,
                          "p_value": hl.p_value}
        except ValueError as e:
            logger.warning("Hosmer-Lemeshow skipped: %s", e)

    # 4. feature importance (featureimportance/*)
    x_np = np.asarray(batch.x)
    em = expected_magnitude_importance(np.asarray(fe.coefficients.means),
                                       np.abs(x_np).mean(0), names, args.top_k)
    vi = variance_importance(np.asarray(fe.coefficients.means),
                             x_np.var(0), names, args.top_k)
    ch = doc.chapter("Feature importance")
    ch.section("Expected magnitude |w|*E|x|").add(
        Table(["feature", "importance"], [[n, f"{v:.5g}"] for n, v in em.ranked]))
    ch.section("Variance w^2*Var[x]").add(
        Table(["feature", "importance"], [[n, f"{v:.5g}"] for n, v in vi.ranked]))

    # 5. residual independence (KendallTauAnalysis.scala)
    kt = kendall_tau_analysis(preds, y, seed=args.seed)
    doc.chapter("Residuals").section("Kendall tau (prediction vs error)").add(
        Text(kt.summary()))

    os.makedirs(args.output_dir, exist_ok=True)
    with open(os.path.join(args.output_dir, "report.html"), "w") as f:
        f.write(render_html(doc))
    with open(os.path.join(args.output_dir, "report.txt"), "w") as f:
        f.write(render_text(doc))
    summary = {
        "coordinate": cid,
        "bootstrap": {"replicates": report.num_replicates,
                      "mean_loss": [mean, std]},
        "fitting": fit_payload,
        "hosmer_lemeshow": hl_payload,
        "kendall_tau": {"tau": kt.tau, "p_value": kt.p_value},
    }
    with open(os.path.join(args.output_dir, "diagnostics.json"), "w") as f:
        json.dump(summary, f, indent=2)
    logger.info("report -> %s", os.path.join(args.output_dir, "report.html"))
    return 0


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()
