"""Multi-process GLMix training driver: every host runs THIS SAME program
under ``jax.distributed`` (reference analog: the Spark cluster executing
GameTrainingDriver — driver loop + executors; here there is no driver
process, SURVEY §5 "Distributed communication backend").

    # on every host h of N (shared filesystem for --output-dir):
    python -m photon_ml_tpu.cli.train_multihost \
        --train-data data.avro --feature-shards g,u --id-tags userId \
        --fixed  "name=fixed,feature.shard=g,reg.weights=0.1" \
        --random "name=user,random.effect.type=userId,feature.shard=u,reg.weights=1" \
        --coordinator-address host0:1234 --num-processes N --process-id h \
        --output-dir out

Layout (parallel/multihost.py): the fixed effect trains on globally
row-sharded data (each host keeps its row range; the one DCN all-reduce),
random effects train on entity-sharded buckets (each host owns the
entities ``process_entity_assignment`` hashes to it, bucketing with GLOBAL
row ids so reservoir decisions are topology-invariant), and
``multihost_glmix_sweep`` runs the residual descent with global score
vectors.  Model output is the reference's executor-partitioned layout:
every host writes its entities as ``part-{pid:05d}.avro`` into the shared
model directory (process 0 adds the fixed effect + metadata); the standard
loader merges the directory.

Multihost v1 contract (see ``multihost_glmix_sweep``): ONE fixed + ONE
random-effect coordinate, dense fixed shard; the random-effect shard may
be dense or sparse (compact observed-column buckets).  Shared-context
normalization (``--normalization``) is supported on dense shards: solves
run transformed, the published models are original-space — the same
semantics as the single-process driver; compact buckets would need
per-lane projected contexts and stay identity-normalized.  Each host
currently scans the full input and keeps its share — a per-host
pre-partitioned read (the reference's partitioned-HDFS layout) drops in
through the same ``row_ids`` contract.
"""

from __future__ import annotations

import argparse
import logging
import sys
from typing import List, Optional

import numpy as np

logger = logging.getLogger("photon_ml_tpu.train_multihost")


def _parse_mesh(spec: str):
    out = {"entity": 1, "feature": 1}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        k, v = part.split("=", 1)
        if k not in out:
            raise ValueError(f"--mesh key {k!r} (multihost meshes take "
                             "entity=/feature=; data fills the rest)")
        out[k] = int(v)
    return out


def run(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="photon-tpu-train-multihost",
        description="Multi-process GLMix training (one fixed + one "
                    "random-effect coordinate) under jax.distributed")
    ap.add_argument("--train-data", nargs="+", required=True)
    ap.add_argument("--feature-shards", required=True)
    ap.add_argument("--id-tags", required=True)
    ap.add_argument("--fixed", required=True,
                    help="fixed-effect coordinate spec (config grammar; "
                         "single reg weight)")
    ap.add_argument("--random", required=True,
                    help="random-effect coordinate spec (config grammar; "
                         "single reg weight)")
    ap.add_argument("--task", default="LOGISTIC_REGRESSION")
    ap.add_argument("--iterations", type=int, default=2)
    ap.add_argument("--coordinator-address", default=None)
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--expected-processes", type=int, default=None)
    ap.add_argument("--mesh", default="entity=1,feature=1",
                    help="entity=E,feature=F axes INSIDE each process "
                         "(ICI); the data axis strides processes (DCN)")
    ap.add_argument("--sparse-threshold", type=int, default=100_000,
                    help="random-effect shards at least this wide read as "
                         "row-sparse and train in compact buckets")
    ap.add_argument("--normalization", default="NONE",
                    choices=["NONE", "SCALE_WITH_MAX_MAGNITUDE",
                             "SCALE_WITH_STANDARD_DEVIATION",
                             "STANDARDIZATION"],
                    help="shared per-shard contexts from training stats; "
                         "solves run transformed, published models are "
                         "original-space (dense shards only)")
    ap.add_argument("--index-map-dir", default=None)
    ap.add_argument("--no-intercept", action="store_true")
    ap.add_argument("--output-dir", required=True)
    ap.add_argument("--checkpoint-dir", default=None,
                    help="per-host npz checkpoint after every iteration; "
                         "rerunning the same command resumes at the cursor "
                         "(requires the same process count and inputs)")
    ap.add_argument("--stop-after-iteration", type=int, default=None,
                    help="exit cleanly right after checkpointing this "
                         "iteration (preemption drills / tests)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.stop_after_iteration is not None and not args.checkpoint_dir:
        raise SystemExit("--stop-after-iteration needs --checkpoint-dir")

    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")

    from photon_ml_tpu.cli.config_grammar import parse_coordinate_spec
    from photon_ml_tpu.game.config import FixedEffectConfig, RandomEffectConfig
    from photon_ml_tpu.types import TaskType

    task = TaskType[args.task]
    fixed_spec = parse_coordinate_spec(args.fixed)
    re_spec = parse_coordinate_spec(args.random)
    if not isinstance(fixed_spec.template, FixedEffectConfig):
        raise SystemExit("--fixed must be a fixed-effect coordinate spec")
    if not isinstance(re_spec.template, RandomEffectConfig):
        raise SystemExit("--random must be a random-effect spec "
                         "(random.effect.type=...)")
    if len(fixed_spec.reg_weights) != 1 or len(re_spec.reg_weights) != 1:
        raise SystemExit("multihost training takes ONE reg weight per "
                         "coordinate (grid/tuning runs are the "
                         "single-process driver's job)")
    fixed_cfg = fixed_spec.with_weight(fixed_spec.reg_weights[0])
    re_cfg = re_spec.with_weight(re_spec.reg_weights[0])

    # 1. cluster up FIRST (jax.distributed before any device use)
    import os

    import jax

    # honor JAX_PLATFORMS even where site hooks pre-import jax (the env var
    # is only read at import time, so on such hosts it would otherwise be
    # silently ignored and the cluster would try to form on the site's
    # default accelerator platform)
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)

    from photon_ml_tpu.parallel import multihost as mh

    mh.initialize(coordinator_address=args.coordinator_address,
                  num_processes=args.num_processes,
                  process_id=args.process_id,
                  expected_processes=args.expected_processes)
    pid, nproc = jax.process_index(), jax.process_count()
    axes = _parse_mesh(args.mesh)
    mesh = mh.global_mesh(n_entity=axes["entity"], n_feature=axes["feature"])
    logger.info("process %d/%d, global mesh %s", pid, nproc, dict(mesh.shape))

    # 2. index maps + data (every host scans the same input -> identical
    # maps and EntityIndex numbering, no exchange needed)
    from photon_ml_tpu.data.index_map import build_index_maps_from_avro
    from photon_ml_tpu.data.reader import read_game_data_avro

    shards = [s.strip() for s in args.feature_shards.split(",") if s.strip()]
    id_tags = [t.strip() for t in args.id_tags.split(",") if t.strip()]
    if args.index_map_dir:
        import os

        from photon_ml_tpu.data.index_map import load_index

        index_maps = {}
        for s in shards:
            for name in (f"{s}.idx", f"{s}.phidx"):
                p = os.path.join(args.index_map_dir, name)
                if os.path.exists(p):
                    index_maps[s] = load_index(p)
                    break
            else:
                raise SystemExit(f"no index map for shard {s!r}")
    else:
        index_maps = build_index_maps_from_avro(
            args.train_data, {s: [] for s in shards},
            add_intercept=not args.no_intercept)
    re_shard = re_cfg.feature_shard
    sparse_shards = ({re_shard}
                     if index_maps[re_shard].size >= args.sparse_threshold
                     else set())
    data, entity_indexes = read_game_data_avro(
        args.train_data, index_maps, id_tag_names=id_tags,
        sparse_shards=sparse_shards)
    n = data.num_samples
    logger.info("%d samples; shards %s%s", n,
                {s: index_maps[s].size for s in shards},
                f" (sparse: {sorted(sparse_shards)})" if sparse_shards else "")

    from photon_ml_tpu.game.data import SparseShard

    fixed_x = data.features[fixed_cfg.feature_shard]
    if isinstance(fixed_x, SparseShard):
        raise SystemExit(
            "multihost v1 trains a DENSE fixed shard — raise "
            "--sparse-threshold past its width; note that maps built from "
            "the data are SHARED by every shard (one vocabulary), so a "
            "sparse random-effect shard with a dense fixed shard needs "
            "distinct per-shard maps via --index-map-dir")

    # shared normalization contexts from training stats (every host scans
    # the same data -> identical contexts; same semantics as the
    # single-process driver's prepareNormalizationContext analog)
    from photon_ml_tpu.core.normalization import (NormalizationType,
                                                  build_normalization,
                                                  compute_feature_stats,
                                                  no_normalization)

    norm_kind = NormalizationType[args.normalization]
    norms = {}
    if norm_kind != NormalizationType.NONE:
        if isinstance(data.features[re_cfg.feature_shard], SparseShard):
            raise SystemExit(
                "multihost --normalization needs DENSE shards (compact "
                "buckets would need per-lane projected contexts — the "
                "single-process driver's domain)")
        import jax.numpy as jnp

        for s in {fixed_cfg.feature_shard, re_cfg.feature_shard}:
            stats = compute_feature_stats(
                jnp.asarray(np.asarray(data.features[s])),
                jnp.asarray(data.weight),
                intercept_index=index_maps[s].intercept_index)
            norms[s] = build_normalization(norm_kind, stats)
    fixed_norm = norms.get(fixed_cfg.feature_shard, no_normalization())
    re_norm = norms.get(re_cfg.feature_shard, no_normalization())
    fixed_ii = index_maps[fixed_cfg.feature_shard].intercept_index
    re_ii = index_maps[re_cfg.feature_shard].intercept_index

    # 3. fixed side: this host's row range, padded, assembled globally
    from photon_ml_tpu.core.batch import DenseBatch

    start, stop = mh.process_row_range(n)
    rows_per = mh.padded_per_host_rows(n, mesh)
    blk = mh.pad_local_rows(
        dict(x=np.asarray(fixed_x[start:stop]), y=data.y[start:stop],
             offset=data.offset[start:stop], weight=data.weight[start:stop]),
        rows_per)
    g = mh.global_batch_from_local(blk, mesh)
    fixed_batch = DenseBatch(x=g["x"], y=g["y"], offset=g["offset"],
                             weight=g["weight"])

    # 4. random-effect side: entity-hash ownership, host-local bucketing
    # with GLOBAL row ids
    from photon_ml_tpu.parallel.bucketing import (bucket_by_entity,
                                                  bucket_by_entity_sparse)

    re_type = re_cfg.random_effect_type
    if re_type not in data.id_tags:
        raise SystemExit(f"id tag {re_type!r} not in --id-tags")
    uids = data.id_tags[re_type]
    rid = mh.local_entity_rows(uids, seed=args.seed)
    logger.info("host owns %d rows across its entities", len(rid))
    n_glob = rows_per * nproc
    xu = data.features[re_shard]
    common = dict(active_cap=re_cfg.active_cap,
                  min_active_samples=re_cfg.min_active_samples,
                  seed=args.seed, row_ids=rid, num_samples=n_glob)
    padded_projs = None
    if isinstance(xu, SparseShard):
        if re_cfg.active_cap is not None:
            raise SystemExit(
                "multihost v1: reservoir caps need the passive scoring "
                "path, which doesn't compose with compact buckets — drop "
                "active.data.upper.bound or densify the shard")
        local, projs = bucket_by_entity_sparse(
            uids[rid], xu.indices[rid], xu.values[rid], xu.dim, data.y[rid],
            offset=data.offset[rid], weight=data.weight[rid], **common)
        gb, padded_projs = mh.global_entity_buckets(local, mesh,
                                                    projections=projs)
    else:
        local = bucket_by_entity(
            uids[rid], np.asarray(xu)[rid], data.y[rid],
            offset=data.offset[rid], weight=data.weight[rid], **common)
        gb = mh.global_entity_buckets(local, mesh)
    scoring = None
    if re_cfg.active_cap is not None:
        ls = bucket_by_entity(
            uids[rid], np.asarray(xu)[rid], data.y[rid],
            offset=data.offset[rid], weight=data.weight[rid],
            min_active_samples=re_cfg.min_active_samples,
            seed=args.seed, row_ids=rid, num_samples=n_glob)
        scoring = mh.build_re_scoring(gb, ls, mesh)

    # 5. the sweep (+ per-iteration checkpointing: every host writes ITS
    # lane blocks, process 0 advances the cursor AFTER a barrier — a rerun
    # of the same command resumes at the cursor with recomputed scores)
    import json
    import os

    from jax.experimental import multihost_utils

    from photon_ml_tpu.core.losses import loss_for_task
    from photon_ml_tpu.core.objective import GLMObjective

    initial, start_it = None, 0
    ck = args.checkpoint_dir
    if ck:
        os.makedirs(ck, exist_ok=True)
        cursor_p = os.path.join(ck, "cursor.json")
        host_p = os.path.join(ck, f"host-{pid:05d}.npz")
        if os.path.exists(cursor_p):
            with open(cursor_p) as f:
                cur = json.load(f)
            if cur["num_processes"] != nproc:
                raise SystemExit(
                    f"checkpoint was written by {cur['num_processes']} "
                    f"processes; this run has {nproc} (lane blocks are "
                    "per-host — resume with the same topology)")
            if not os.path.exists(host_p):
                raise SystemExit(
                    f"checkpoint cursor exists but {host_p} is missing — "
                    "every host's npz must be present (lane blocks are "
                    "per-host; copy the whole checkpoint dir)")
            z = np.load(host_p)
            start_it = int(cur["next_iteration"])
            if int(z["iteration"]) != start_it - 1:
                # a preemption between the block write and the cursor
                # commit leaves blocks/cursor from different iterations —
                # resuming would warm-start a state on NO point of the
                # uninterrupted trajectory
                raise SystemExit(
                    f"checkpoint inconsistent: {host_p} holds iteration "
                    f"{int(z['iteration'])} but cursor expects "
                    f"{start_it - 1} — restart from scratch or restore a "
                    "consistent checkpoint dir")
            initial = (z["w_fixed"],
                       [z[f"b{i}"] for i in range(int(z["n_buckets"]))])
            logger.info("resuming at iteration %d from %s", start_it, ck)
        # every host must enter the sweep with the SAME trip count — a
        # stale cursor view (NFS attribute caching, partial mounts) would
        # otherwise deadlock the first collective
        from jax.experimental import multihost_utils as _mhu

        views = np.asarray(_mhu.process_allgather(
            np.asarray([start_it], np.int64)))
        if len(set(views.ravel().tolist())) != 1:
            raise SystemExit(
                f"hosts disagree on the resume iteration ({views.ravel()}) "
                "— the checkpoint dir is not uniformly visible")

    def on_iteration(it, wf, coeffs):
        if not ck:
            return
        blocks = mh.host_lane_blocks(coeffs)
        arrays = {f"b{i}": b for i, b in enumerate(blocks)}
        arrays["w_fixed"] = np.asarray(wf)
        arrays["n_buckets"] = np.asarray(len(blocks))
        arrays["iteration"] = np.asarray(it)
        tmp = host_p + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, host_p)
        multihost_utils.sync_global_devices(f"ckpt blocks {it}")
        if pid == 0:
            tmp = cursor_p + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"next_iteration": it + 1,
                           "num_processes": nproc}, f)
            os.replace(tmp, cursor_p)
        multihost_utils.sync_global_devices(f"ckpt cursor {it}")
        if args.stop_after_iteration is not None \
                and it >= args.stop_after_iteration:
            logger.info("stopping after iteration %d (checkpointed)", it)
            raise SystemExit(0)

    obj_f = GLMObjective(loss=loss_for_task(task), reg=fixed_cfg.reg,
                         norm=fixed_norm)
    obj_re = GLMObjective(loss=loss_for_task(task), reg=re_cfg.reg,
                          norm=re_norm)
    wf, rec, _ = mh.multihost_glmix_sweep(
        mesh, fixed_batch, gb, obj_f, obj_re,
        num_iterations=args.iterations,
        optimizer=fixed_cfg.optimizer, config=fixed_cfg.solver,
        re_scoring=scoring, num_samples=n,
        on_iteration=on_iteration, initial=initial,
        start_iteration=start_it)
    exported = mh.export_local_random_effects(
        rec, gb, mesh, projections=padded_projs,
        norm=None if re_norm.is_identity else re_norm,
        intercept_index=re_ii)
    logger.info("trained: fixed[%d], %d local entities",
                len(np.asarray(wf)), len(exported))

    # 6. executor-partitioned model write (shared --output-dir): every host
    # writes its entities as part-{pid}; process 0 adds fixed + metadata
    from photon_ml_tpu.models.game import FixedEffectModel, RandomEffectModel
    from photon_ml_tpu.models.glm import Coefficients
    from photon_ml_tpu.storage.model_io import (FORMAT_VERSION,
                                                save_coordinate,
                                                save_random_effect_part)

    os.makedirs(args.output_dir, exist_ok=True)
    eids = sorted(exported)
    w_stack = (np.stack([exported[e] for e in eids]) if eids
               else np.zeros((0, index_maps[re_shard].size), np.float32))
    re_model = RandomEffectModel(
        w_stack=w_stack, slot_of={e: i for i, e in enumerate(eids)},
        random_effect_type=re_type, feature_shard=re_shard, task=task)
    re_info = save_random_effect_part(
        re_spec.name, re_model, args.output_dir, index_maps[re_shard],
        entity_indexes.get(re_type), part=pid)
    # metadata.json is the completion signal readers poll for — it must not
    # appear while a peer is still writing its part file
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("model parts written")
    if pid == 0:
        fixed_model = FixedEffectModel(
            coefficients=Coefficients(means=np.asarray(
                wf if fixed_norm.is_identity
                else fixed_norm.model_to_original_space(wf, fixed_ii))),
            feature_shard=fixed_cfg.feature_shard, task=task)
        fixed_info = save_coordinate(fixed_spec.name, fixed_model,
                                     args.output_dir, index_maps)
        meta = {"version": FORMAT_VERSION, "task": task.value,
                "coordinates": {fixed_spec.name: fixed_info,
                                re_spec.name: re_info}}
        with open(os.path.join(args.output_dir, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=2)
        from photon_ml_tpu.data.native_index import StoreIndexMap

        for s2 in shards:
            ext = (".phidx" if isinstance(index_maps[s2], StoreIndexMap)
                   else ".idx")
            index_maps[s2].save(os.path.join(args.output_dir, f"{s2}{ext}"))
        for tag, eidx in entity_indexes.items():
            eidx.save(os.path.join(args.output_dir,
                                   f"{tag}.entities.json"))
    logger.info("process %d wrote its model part -> %s", pid,
                args.output_dir)
    return 0


def main() -> None:
    sys.exit(run())


if __name__ == "__main__":
    main()
