"""GAME online scoring driver — JSON-lines in, JSON-lines out.

Photon ML reference counterpart: there is none in the batch repo — the
reference's GameScoringDriver scores offline datasets; online traffic is
served by LinkedIn infrastructure reading the published PalDB stores.  This
driver IS that online layer for the TPU-native stack: it loads a training
output directory into a device-resident ``serving.CoefficientStore``,
AOT-warms the ``serving.ScoringEngine`` bucket ladder, then scores a
stream of JSON-lines requests with micro-batching and supports atomic hot
model swap mid-stream.

Wire protocol (one JSON object per line on stdin / ``--requests`` file):

  request   {"uid": 7, "features": [{"name": "g0", "term": "", "value": 0.3},
             ...], "ids": {"userId": "user3"}, "offset": 0.0}
            (features also accept compact [name, value] / [name, term,
             value] lists)
  flush     a blank line — score the buffered requests now (otherwise the
            batcher flushes whenever ``--max-batch`` requests are buffered,
            and at EOF)
  swap      {"cmd": "swap", "model_dir": "/path/to/new/output"}
            -> {"swap": "ok"|"rejected", ...}; a rejected swap (corrupt or
            incomplete model dir) leaves the current version serving
  metrics   {"cmd": "metrics"} -> one metrics JSON line

Responses are ``{"uid": ..., "score": ...}`` lines on stdout, in request
order.  Programmatic use: ``build_server`` returns the (engine, swapper)
pair without touching stdio.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import IO, List, Optional, Sequence, Tuple

from photon_ml_tpu.serving.batcher import BucketedBatcher, request_from_json
from photon_ml_tpu.serving.coefficient_store import CoefficientStore, StoreConfig
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.swap import HotSwapper
from photon_ml_tpu.storage.model_io import ModelLoadError, load_model_bundle

logger = logging.getLogger("photon_ml_tpu.serve")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-tpu-serve",
                                description="Online scoring with a trained "
                                            "GAME model (JSON-lines)")
    p.add_argument("--model-dir", required=True,
                   help="training output dir (best/, *.idx, *.entities.json) "
                        "or a model dir with metadata.json")
    p.add_argument("--max-batch", type=int, default=64,
                   help="micro-batch flush threshold and top bucket size")
    p.add_argument("--buckets", default="",
                   help="comma list of bucket sizes (default: powers of two "
                        "up to --max-batch)")
    p.add_argument("--device-entity-capacity", type=int, default=0,
                   help="max entity rows device-resident per coordinate "
                        "(0 = all; colder entities serve from the host LRU "
                        "fallback)")
    p.add_argument("--lru-capacity", type=int, default=4096,
                   help="host LRU entries per coordinate for cold entities")
    p.add_argument("--predict-mean", action="store_true",
                   help="emit inverse-link means instead of raw margins")
    p.add_argument("--no-warm", action="store_true",
                   help="skip AOT pre-compilation of the bucket ladder "
                        "(first request per bucket then pays the compile)")
    p.add_argument("--requests", default="-",
                   help="JSON-lines request file ('-' = stdin)")
    p.add_argument("--metrics-json", default="",
                   help="write the final metrics snapshot here at exit")
    return p


def build_server(model_dir: str,
                 max_batch: int = 64,
                 bucket_sizes: Optional[Sequence[int]] = None,
                 device_entity_capacity: Optional[int] = None,
                 lru_capacity: int = 4096,
                 metrics: Optional[ServingMetrics] = None,
                 warm: bool = True) -> Tuple[ScoringEngine, HotSwapper]:
    """Programmatic entry point: load -> store -> engine (+ warmed ladder)
    -> swapper.  Raises storage.model_io.ModelLoadError on a broken dir."""
    metrics = metrics or ServingMetrics()
    bundle = load_model_bundle(model_dir)
    config = StoreConfig(device_capacity=device_entity_capacity,
                         lru_capacity=lru_capacity)
    store = CoefficientStore.from_bundle(bundle, config=config,
                                         version=model_dir, metrics=metrics)
    engine = ScoringEngine(store, BucketedBatcher(max_batch, bucket_sizes),
                           metrics=metrics)
    if warm:
        n = engine.warm()
        logger.info("warmed %d executable(s) over buckets %s", n,
                    engine.batcher.bucket_sizes)
    return engine, HotSwapper(engine)


def _serve_stream(engine: ScoringEngine, swapper: HotSwapper, lines: IO,
                  out: IO, predict_mean: bool) -> int:
    buffered: List = []

    def flush() -> None:
        if not buffered:
            return
        scores = engine.score_requests(buffered, predict_mean=predict_mean)
        for req, s in zip(buffered, scores):
            out.write(json.dumps({"uid": req.uid, "score": float(s)}) + "\n")
        out.flush()
        buffered.clear()

    for line in lines:
        line = line.strip()
        if not line:
            flush()
            continue
        try:
            obj = json.loads(line)
        except ValueError as e:
            logger.error("bad request line: %s", e)
            out.write(json.dumps({"error": str(e)}) + "\n")
            continue
        cmd = obj.get("cmd") if isinstance(obj, dict) else None
        if cmd == "swap":
            flush()  # everything buffered scores on the pre-swap version
            ok = swapper.swap(obj["model_dir"])
            out.write(json.dumps({
                "swap": "ok" if ok else "rejected",
                "generation": engine.store.generation,
                "version": engine.store.version}) + "\n")
            out.flush()
        elif cmd == "metrics":
            flush()
            out.write(engine.metrics.to_json() + "\n")
            out.flush()
        elif cmd is not None:
            out.write(json.dumps({"error": f"unknown cmd {cmd!r}"}) + "\n")
        else:
            try:
                buffered.append(request_from_json(obj))
            except (ValueError, TypeError) as e:
                logger.error("bad request: %s", e)
                out.write(json.dumps({"error": str(e)}) + "\n")
                continue
            if len(buffered) >= engine.batcher.max_batch:
                flush()
    flush()
    return 0


def run(argv: List[str]) -> int:
    logging.basicConfig(level=logging.INFO, stream=sys.stderr,
                        format="%(asctime)s %(name)s %(message)s")
    args = build_parser().parse_args(argv)

    from photon_ml_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()

    buckets = None
    if args.buckets:
        buckets = [int(b) for b in args.buckets.split(",") if b.strip()]
    try:
        engine, swapper = build_server(
            args.model_dir,
            max_batch=args.max_batch,
            bucket_sizes=buckets,
            device_entity_capacity=(args.device_entity_capacity or None),
            lru_capacity=args.lru_capacity,
            warm=not args.no_warm)
    except (ModelLoadError, ValueError) as e:
        logger.error("--model-dir: %s", e)
        return 1
    logger.info("serving generation %d (version %r), task %s",
                engine.store.generation, engine.store.version,
                engine.store.task.value)

    lines = sys.stdin if args.requests == "-" else open(args.requests)
    try:
        rc = _serve_stream(engine, swapper, lines, sys.stdout,
                           args.predict_mean)
    finally:
        if lines is not sys.stdin:
            lines.close()
        if args.metrics_json:
            engine.metrics.export(args.metrics_json)
            logger.info("metrics -> %s", args.metrics_json)
    return rc


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()
