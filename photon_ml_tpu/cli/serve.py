"""GAME online scoring driver — JSON-lines in, JSON-lines out.

Photon ML reference counterpart: there is none in the batch repo — the
reference's GameScoringDriver scores offline datasets; online traffic is
served by LinkedIn infrastructure reading the published PalDB stores.  This
driver IS that online layer for the TPU-native stack: it loads a training
output directory into a device-resident ``serving.CoefficientStore``,
AOT-warms the ``serving.ScoringEngine`` bucket ladder, then scores a
stream of JSON-lines requests through the ASYNC deadline batcher
(``serving.batcher.AsyncBatcher``: each request is submitted individually
and coalesces with its neighbors until a bucket fills or ``--deadline-us``
expires) and supports atomic hot model swap and streaming coefficient
deltas mid-stream.

Wire protocol (one JSON object per line on stdin / ``--requests`` file):

  request   {"uid": 7, "features": [{"name": "g0", "term": "", "value": 0.3},
             ...], "ids": {"userId": "user3"}, "offset": 0.0}
            (features also accept compact [name, value] / [name, term,
             value] lists)
  flush     a blank line — force-flush the batcher and drain every pending
            score (otherwise the batcher flushes whenever a top bucket
            fills or the deadline expires, and at EOF)
  swap      {"cmd": "swap", "model_dir": "/path/to/new/output"}
            -> {"swap": "ok"|"rejected", ...}; a rejected swap (corrupt or
            incomplete model dir) leaves the current version serving
  delta     {"cmd": "delta", "coordinate": "user", "entity": "user3",
             "row": [0.1, ...]}
            -> {"delta": "ok"|"rejected", "delta_version": n}; scatters one
            online-learned coefficient row into the live generation (device
            table when hot, host archive + LRU invalidation always) — no
            generation flip, no recompile
  rebalance {"cmd": "rebalance"} -> {"rebalance": {cid: [promoted,
            demoted]}}; one synchronous frequency-ranked hot-set pass (the
            background cadence is ``--hot-set-interval``)
  metrics   {"cmd": "metrics"} -> one metrics JSON line;
            {"cmd": "metrics", "format": "prometheus"} ->
            {"prometheus": "<text exposition>"} (the full labeled registry)
  trace     {"cmd": "trace"} -> one Chrome ``trace_event`` JSON line
            (load in Perfetto) covering the tracer ring buffer: submit ->
            batch flush -> resolve -> AOT execute spans; needs ``--trace``
            (otherwise -> {"error": ...})
  flight    {"cmd": "flight"} -> {"flight": {"spool_dir", "dumps",
            "latest"}} — the flight recorder's spool index plus the most
            recent degradation dump; needs ``--flight-dir`` (otherwise
            -> {"error": ...})
  watch     {"cmd": "watch"} -> {"watch": <snapshot frame>} — photonwatch
            federation: the first reply per stream is a full structured
            registry snapshot, every later one a delta of the series that
            moved since (obs/watch/federation.py); feed the frames to a
            ``FleetView`` (or ``tools/fleetwatch.py``) to aggregate many
            processes into one fleet registry

Responses are ``{"uid": ..., "score": ...}`` lines on stdout, in request
order.  Every command drains pending requests first, so everything
submitted before a swap/delta line scores on the pre-swap/pre-delta
coefficients.  Programmatic use: ``build_server`` returns the (engine,
swapper) pair without touching stdio.

``--listen host:port`` serves the SAME wire protocol over TCP instead of
stdio, through the ``serving.frontend`` edge: many concurrent clients,
deadline-budget admission control (``{"error": "overloaded",
"retry_after_ms": ...}`` when the predicted queue wait exceeds
``--admission-budget-ms``), per-client round-robin fairness, and graceful
drain on swap / ``{"cmd": "shutdown"}`` / SIGTERM.  ``--metrics-port``
additionally exposes ``GET /metrics`` (Prometheus text exposition) on
localhost in either mode.  Input lines in both modes are byte-bounded
(``--max-line-bytes``): an oversized line gets an ``{"error": ...}`` reply
and the stream keeps going.

``--delta-log DIR`` makes this process a photonlearn REPLICA: the
delta log a ``cli/learn.py`` trainer writes is replayed into the store
before serving, tailed on a background thread (``--delta-log-poll``), and
replayed onto every hot-swapped-in generation before it activates — so a
second serving process converges to the trainer's live coefficients with
no coordination beyond the shared log directory (see online/catchup.py).

``--add-model NAME=DIR[,tenant=T]`` (repeatable) turns the process into a
photonfleet node: the primary ``--model-dir`` registers under
``--model-name`` and every added directory becomes another model handle on
the SAME AOT kernel cache and device hot-row budget (``--fleet-budget``,
``--tenant-quota T=ROWS``).  Requests grow an optional ``"model"`` field
(absent -> the default model, so existing clients keep working), control
commands grow ``fleet`` / ``canary`` / ``promote`` / ``rollback`` /
``shadow`` plus ``"model"`` routing on swap/delta/rebalance, and in
``--listen`` mode ``--tenant-token T=TOK`` scopes connections to one
tenant's models while ``--tenant-budget-ms`` sheds a bursting tenant alone
(reason ``tenant_overload``).

``--subscribe host:port`` removes even that shared directory: the process
connects to a photonrepl owner (``learn.py --repl-listen``, or any
``online.replication.ReplicationServer``), bootstraps its base model from
a checksummed snapshot tarstream into ``--spool``, mirrors the owner's
live record stream into a local delta log there, and serves from the
mirror exactly as ``--delta-log`` would — including
replay-before-activate when the owner hot-swaps mid-stream (the new base
ships inline and this process swaps to it).  A restarted replica with a
warm spool resumes from its last applied identity (log replay when the
owner still retains it, fresh snapshot otherwise).  ``--auth-token``
(default ``$PHOTON_AUTH_TOKEN``) is presented to the owner AND required
of clients on ``--listen``.
"""

from __future__ import annotations

import argparse
import asyncio
import collections
import json
import logging
import os
import signal
import sys
from typing import IO, List, Optional, Sequence, Tuple

from photon_ml_tpu.obs.trace import span as obs_span
from photon_ml_tpu.serving.batcher import BucketedBatcher, request_from_json
from photon_ml_tpu.serving.frontend.protocol import (DEFAULT_MAX_LINE_BYTES,
                                                     LineTooLong,
                                                     iter_bounded_lines)
from photon_ml_tpu.serving.coefficient_store import (CoefficientStore,
                                                     HotSetManager,
                                                     StoreConfig)
from photon_ml_tpu.serving.engine import ScoringEngine
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.swap import HotSwapper
from photon_ml_tpu.storage.model_io import ModelLoadError, load_model_bundle

logger = logging.getLogger("photon_ml_tpu.serve")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="photon-tpu-serve",
                                description="Online scoring with a trained "
                                            "GAME model (JSON-lines)")
    p.add_argument("--model-dir", default="",
                   help="training output dir (best/, *.idx, *.entities.json) "
                        "or a model dir with metadata.json.  Required "
                        "unless --subscribe bootstraps the base instead")
    p.add_argument("--max-batch", type=int, default=64,
                   help="micro-batch flush threshold and top bucket size")
    p.add_argument("--buckets", default="",
                   help="comma list of bucket sizes (default: powers of two "
                        "up to --max-batch)")
    p.add_argument("--deadline-us", type=float, default=500.0,
                   help="async batcher deadline: a pending request waits at "
                        "most this long for a bucket to fill before its "
                        "batch flushes anyway")
    p.add_argument("--sync-batcher", action="store_true",
                   help="legacy synchronous batching: buffer requests and "
                        "flush at --max-batch / blank line / EOF instead of "
                        "the async deadline accumulator")
    p.add_argument("--device-entity-capacity", type=int, default=0,
                   help="max entity rows device-resident per coordinate "
                        "(0 = all; colder entities serve from the host LRU "
                        "fallback and rebalancing promotes the hottest)")
    p.add_argument("--mesh-shards", type=int, default=0,
                   help="partition every random-effect coefficient table "
                        "over this many devices (parallel/mesh.serving_mesh "
                        "axis 'shard'); 0 = unsharded.  When set, "
                        "--device-entity-capacity is the PER-SHARD hot-row "
                        "budget, so aggregate hot capacity scales with the "
                        "shard count")
    p.add_argument("--no-load-aware-routing", action="store_true",
                   help="freeze sharded entity->shard routing at the "
                        "round-robin (archive slot %% N) layout instead of "
                        "re-fitting it to observed traffic at each "
                        "rebalance — the pre-traffic-aware router, kept "
                        "for A/B comparison (scores are bitwise identical "
                        "either way; only placement and hit rate differ)")
    p.add_argument("--replicate-top-k", type=int, default=0,
                   help="give the K hottest entities hot residency on "
                        "EVERY mesh shard (reads stay shard-local, "
                        "streaming deltas fan out to all replicas under "
                        "one generation/delta_version) — flattens a zipf "
                        "head that one shard's hot budget cannot hold "
                        "(0 = off; needs --mesh-shards)")
    p.add_argument("--lru-capacity", type=int, default=4096,
                   help="host LRU entries per coordinate for cold entities")
    p.add_argument("--hot-set-interval", type=float, default=0.0,
                   help="seconds between background frequency-ranked "
                        "promotion/demotion passes (0 = only on "
                        "{\"cmd\": \"rebalance\"})")
    p.add_argument("--hot-decay", type=float, default=0.5,
                   help="EWMA decay applied to entity hit counters at each "
                        "rebalance pass")
    p.add_argument("--predict-mean", action="store_true",
                   help="emit inverse-link means instead of raw margins")
    p.add_argument("--no-warm", action="store_true",
                   help="skip AOT pre-compilation of the bucket ladder "
                        "(first request per bucket then pays the compile)")
    p.add_argument("--requests", default="-",
                   help="JSON-lines request file ('-' = stdin)")
    p.add_argument("--listen", default="",
                   help="host:port — serve the wire protocol over TCP "
                        "through the serving.frontend edge (admission "
                        "control, per-client fairness, graceful drain) "
                        "instead of stdio; port 0 picks an ephemeral port "
                        "(logged at startup)")
    p.add_argument("--metrics-port", type=int, default=0,
                   help="expose GET /metrics (Prometheus text exposition) "
                        "and /metrics.json on this localhost port "
                        "(0 = off; in --listen mode it shares the event "
                        "loop, in stdio mode it runs on a sidecar thread)")
    p.add_argument("--max-line-bytes", type=int,
                   default=DEFAULT_MAX_LINE_BYTES,
                   help="hard per-line byte bound on every input stream; "
                        "an oversized line is discarded with an "
                        "{\"error\": ...} reply and the stream survives")
    p.add_argument("--admission-budget-ms", type=float, default=50.0,
                   help="--listen mode: per-request deadline budget; "
                        "requests predicted to wait longer are shed with "
                        "{\"error\": \"overloaded\", \"retry_after_ms\"...}")
    p.add_argument("--resume-fraction", type=float, default=0.5,
                   help="--listen mode: hysteresis low watermark as a "
                        "fraction of the budget — shedding latches until "
                        "the predicted wait drops below this")
    p.add_argument("--dispatch-window", type=int, default=0,
                   help="--listen mode: max requests resident in the "
                        "batcher at once; the rest queue per-client where "
                        "round-robin fairness applies (0 = 2 flush waves)")
    p.add_argument("--client-budget-ms", type=float, default=0.0,
                   help="--listen mode: per-CONNECTION deadline budget — a "
                        "client whose own backlog is predicted to wait "
                        "longer is shed alone ({\"error\": \"overloaded\", "
                        "\"reason\": \"client_overload\"}) before the "
                        "global latch trips for everyone (0 = off)")
    p.add_argument("--max-connections", type=int, default=0,
                   help="--listen mode: hard connection-count cap; excess "
                        "accepts get one {\"error\": "
                        "\"too_many_connections\"} reply and a clean close "
                        "(0 = unlimited)")
    p.add_argument("--add-model", action="append", default=[],
                   metavar="NAME=DIR[,tenant=T]",
                   help="register an additional model directory as a fleet "
                        "handle (repeatable): shares the primary engine's "
                        "AOT kernel cache (same-shape models compile "
                        "nothing) and the --fleet-budget hot-row budget; "
                        "tenant defaults to 'default'")
    p.add_argument("--model-name", default="default",
                   help="fleet model id the primary --model-dir registers "
                        "under (only meaningful with --add-model)")
    p.add_argument("--fleet-budget", type=int, default=0,
                   help="fleet-wide device hot-row cap across every "
                        "model's hot tables (0 = unbudgeted); registration "
                        "that would exceed it is refused")
    p.add_argument("--tenant-quota", action="append", default=[],
                   metavar="TENANT=ROWS",
                   help="per-tenant carve-out of --fleet-budget "
                        "(repeatable); a tenant over quota cannot register "
                        "more models and rebalance re-verifies the "
                        "invariant")
    p.add_argument("--tenant-token", action="append", default=[],
                   metavar="TENANT=TOKEN",
                   help="--listen mode: auth token scoping a connection to "
                        "one tenant's models (repeatable; requests for "
                        "another tenant's model get {\"error\": "
                        "\"forbidden\"}).  Turns the auth handshake on "
                        "even without --auth-token")
    p.add_argument("--tenant-budget-ms", type=float, default=0.0,
                   help="--listen mode: per-TENANT deadline budget — a "
                        "tenant whose aggregate backlog is predicted to "
                        "wait longer is shed alone (reason "
                        "\"tenant_overload\") before the global latch "
                        "trips (0 = off)")
    p.add_argument("--shard-budget-ms", type=float, default=0.0,
                   help="--listen mode: per-MESH-SHARD deadline budget — "
                        "requests routed to a shard whose attributable "
                        "backlog is predicted to wait longer are shed "
                        "alone (reason \"shard_overload\") while the cool "
                        "shards keep admitting (0 = off; needs "
                        "--mesh-shards)")
    p.add_argument("--canary-fraction", type=float, default=0.25,
                   help="default traffic fraction a {\"cmd\": \"canary\"} "
                        "episode routes to the candidate (deterministic "
                        "request-key hash split, not RNG)")
    p.add_argument("--canary-min-observations", type=int, default=100,
                   help="default clean-observation window before a canary "
                        "auto-promotes")
    p.add_argument("--canary-max-drift", type=float, default=1e-6,
                   help="default mean |canary - control| score drift above "
                        "which a canary auto-rolls-back")
    p.add_argument("--trace-sample", type=int, default=0,
                   help="sampled always-on tracing: mint a photonpulse "
                        "trace context for every Nth request arriving "
                        "without one (0 = --listen mints for every "
                        "request; stdio mints only when sampling)")
    p.add_argument("--delta-log", default="",
                   help="FOLLOW a photonlearn delta log directory "
                        "(online/delta_log.py): replay it into the store "
                        "before serving, then tail it so rows a trainer "
                        "process publishes become visible here within "
                        "--delta-log-poll seconds; the log is read-only to "
                        "this process and hot swaps replay it onto the "
                        "incoming generation before activation")
    p.add_argument("--delta-log-poll", type=float, default=0.05,
                   help="seconds between delta-log tail polls")
    p.add_argument("--staleness-bound", type=float, default=5.0,
                   help="readiness (/readyz on --metrics-port): maximum "
                        "age of the last successful delta-log catch-up "
                        "pass before this replica reports not-ready; also "
                        "the watchdog's per-worker stall bound")
    p.add_argument("--subscribe", default="",
                   help="host:port of a photonrepl owner (learn.py "
                        "--repl-listen): bootstrap the base model from a "
                        "snapshot over the socket, then live-tail its "
                        "delta stream into a local mirror under --spool — "
                        "no shared directory.  Mutually exclusive with "
                        "--model-dir / --delta-log")
    p.add_argument("--spool", default="",
                   help="replica spool directory for --subscribe "
                        "(mirror log, extracted snapshot bases, resume "
                        "state); reusing it across restarts enables "
                        "identity-based resume")
    p.add_argument("--bootstrap-timeout", type=float, default=60.0,
                   help="--subscribe: seconds to wait for the first "
                        "snapshot (or a warm spool) before giving up")
    p.add_argument("--auth-token", default=None,
                   help="shared secret: presented to the --subscribe "
                        "owner AND required of --listen clients (first "
                        "line {\"cmd\": \"auth\", \"token\": ...}; "
                        "constant-time compare).  Default: "
                        "$PHOTON_AUTH_TOKEN")
    p.add_argument("--metrics-json", default="",
                   help="write the final metrics snapshot here at exit")
    p.add_argument("--trace", action="store_true",
                   help="enable the photonscope tracer (spans across "
                        "submit/flush/resolve/execute; {\"cmd\": \"trace\"} "
                        "dumps the ring buffer as Chrome trace JSON)")
    p.add_argument("--trace-buffer", type=int, default=8192,
                   help="tracer ring-buffer capacity (newest spans win)")
    p.add_argument("--trace-out", default="",
                   help="write the Chrome trace JSON here at exit "
                        "(implies --trace)")
    p.add_argument("--trace-label", default="",
                   help="photonpulse process label stamped on trace "
                        "exports and clock replies (default: 'replica' "
                        "with --subscribe, else 'frontend')")
    p.add_argument("--flight-dir", default="",
                   help="photonpulse flight recorder spool: on a "
                        "degradation transition (health check failure, "
                        "watchdog stall, admission shed latch) the tracer "
                        "ring is dumped here as Chrome trace JSON; "
                        "retrieve via {\"cmd\": \"flight\"} or "
                        "GET /flightz on --metrics-port")
    p.add_argument("--flight-max-bytes", type=int, default=16 << 20,
                   help="on-disk byte bound for the flight spool "
                        "(oldest dumps evicted first)")
    p.add_argument("--watch", action="store_true",
                   help="photonwatch: enable span-aligned XLA device-time "
                        "attribution (xla_device_seconds{site=} + "
                        "device_us/host_us span attrs on serve.execute) — "
                        "the {\"cmd\": \"watch\"} federation stream and "
                        "GET /watchz are always on")
    p.add_argument("--slo", default="", metavar="FILE",
                   help="photonwatch SLO objectives (JSON list, "
                        "obs/watch/slo.py): evaluate multi-window burn "
                        "rates against this process's registry on a "
                        "background thread, publishing "
                        "fleet_slo_burn_rate{slo=} / fleet_slo_alert{slo=} "
                        "and dumping the flight recorder on alert edges")
    p.add_argument("--slo-interval", type=float, default=1.0,
                   help="seconds between --slo evaluation passes")
    p.add_argument("--fleet-burn-budget", type=float, default=0.0,
                   help="--listen mode: shed new requests (reason "
                        "\"fleet_pressure\") while the largest published "
                        "fleet_slo_burn_rate gauge in this process's "
                        "registry exceeds this burn multiple — the hook a "
                        "fleetwatch aggregator (or a local --slo engine) "
                        "drives (0 = off)")
    p.add_argument("--exemplars", action="store_true",
                   help="attach trace-id exemplars to latency histogram "
                        "buckets; with --metrics-port the /metrics route "
                        "switches to OpenMetrics 1.0.0 exposition, the "
                        "format exemplars are specified in (pairs with "
                        "--trace: samples observed outside any trace "
                        "context carry no exemplar)")
    return p


def build_server(model_dir: str,
                 max_batch: int = 64,
                 bucket_sizes: Optional[Sequence[int]] = None,
                 device_entity_capacity: Optional[int] = None,
                 lru_capacity: int = 4096,
                 hot_decay: float = 0.5,
                 mesh_shards: int = 0,
                 metrics: Optional[ServingMetrics] = None,
                 warm: bool = True,
                 delta_log=None,
                 log_owner: bool = True,
                 load_aware_routing: bool = True,
                 replicate_top_k: int = 0
                 ) -> Tuple[ScoringEngine, HotSwapper]:
    """Programmatic entry point: load -> store -> engine (+ warmed ladder)
    -> swapper.  Raises storage.model_io.ModelLoadError on a broken dir.
    ``delta_log``/``log_owner`` attach an ``online.DeltaLog`` to the
    swapper (serving/swap.py module docstring for the owner/follower
    split)."""
    metrics = metrics or ServingMetrics()
    bundle = load_model_bundle(model_dir)
    config = StoreConfig(device_capacity=device_entity_capacity,
                         lru_capacity=lru_capacity, hot_decay=hot_decay,
                         mesh_shards=mesh_shards,
                         load_aware_routing=load_aware_routing,
                         replicate_top_k=replicate_top_k)
    store = CoefficientStore.from_bundle(bundle, config=config,
                                         version=model_dir, metrics=metrics)
    engine = ScoringEngine(store, BucketedBatcher(max_batch, bucket_sizes),
                           metrics=metrics)
    if warm:
        n = engine.warm()
        logger.info("warmed %d executable(s) over buckets %s", n,
                    engine.batcher.bucket_sizes)
    swapper = HotSwapper(engine, delta_log=delta_log, log_owner=log_owner)
    swapper.set_base(model_dir)  # snapshot source for photonrepl owners
    return engine, swapper


def _serve_stream(engine: ScoringEngine, swapper: HotSwapper, lines: IO,
                  out: IO, predict_mean: bool,
                  deadline_s: float = 500e-6,
                  sync: bool = False,
                  max_line_bytes: int = DEFAULT_MAX_LINE_BYTES,
                  fleet=None, health=None,
                  canary_defaults: Optional[dict] = None,
                  trace_sample_n: int = 0) -> int:
    """Drive the engine from a JSON-lines stream.

    Async (default): each request is submitted to an AsyncBatcher and its
    (uid, future) queued; completed scores are written opportunistically in
    submission order, and every command / blank line / EOF force-flushes
    and drains.  ``sync=True`` keeps the legacy buffer-then-score path.

    Fleet mode (``fleet=ModelFleet``): requests route by their optional
    ``"model"`` field to per-model AsyncBatchers scoring through a
    ``FleetRouter``, so canary episodes and shadow scorers interpose per
    model; the canary/promote/rollback/shadow/fleet commands drive them.
    """
    router = None
    batchers: dict = {}  # model_id -> AsyncBatcher (fleet mode)
    if fleet is not None:
        from photon_ml_tpu.serving.fleet.router import FleetRouter
        router = FleetRouter(fleet, health=health)
        if sync:
            logger.warning("--sync-batcher is ignored in fleet mode "
                           "(per-model async batchers)")
            sync = False
    pending: "collections.deque" = collections.deque()  # (uid, future)
    buffered: List = []  # sync mode only
    watch_exporter: List = []  # per-stream photonwatch DeltaExporter (lazy)
    batcher = None if (sync or fleet is not None) else engine.async_batcher(
        deadline_s=deadline_s, predict_mean=predict_mean)

    def model_batcher(model_id: str):
        b = batchers.get(model_id)
        if b is None:
            from photon_ml_tpu.serving.batcher import AsyncBatcher
            handle = fleet.handle(model_id)

            def score(reqs, _mid=model_id):
                return router.score(_mid, reqs, predict_mean=predict_mean)

            b = AsyncBatcher(score,
                             flush_threshold=handle.engine.batcher.max_batch,
                             deadline_s=deadline_s,
                             metrics=handle.engine.metrics)
            batchers[model_id] = b
        return b

    def all_batchers():
        if fleet is not None:
            return list(batchers.values())
        return [] if batcher is None else [batcher]

    def cmd_target(obj):
        """(swapper, store) a control command acts on: the optional
        ``"model"`` field routes in fleet mode.  None after writing the
        error reply for an unknown model."""
        if fleet is None:
            return swapper, engine.store
        try:
            h = fleet.resolve(obj.get("model"))
        except ValueError as e:
            out.write(json.dumps({"error": str(e)}) + "\n")
            out.flush()
            return None
        return h.swapper, h.engine.store

    def emit(uid, fut) -> None:
        with obs_span("serve.respond", uid=uid):
            try:
                out.write(json.dumps({"uid": uid,
                                      "score": fut.result()}) + "\n")
            except Exception as e:  # scoring error: the request's own line
                out.write(json.dumps({"uid": uid, "error": str(e)}) + "\n")

    def drain(block: bool) -> None:
        wrote = False
        while pending and (block or pending[0][1].done()):
            emit(*pending.popleft())
            wrote = True
        if wrote:
            out.flush()

    def flush() -> None:
        if sync:
            if not buffered:
                return
            scores = engine.score_requests(buffered,
                                           predict_mean=predict_mean)
            for req, s in zip(buffered, scores):
                out.write(json.dumps({"uid": req.uid,
                                      "score": float(s)}) + "\n")
            out.flush()
            buffered.clear()
        else:
            for b in all_batchers():
                b.flush()
            drain(block=True)

    try:
        for line in iter_bounded_lines(lines, max_line_bytes):
            if isinstance(line, LineTooLong):
                # oversized line: already discarded through its newline by
                # the bounded reader — reply and keep serving
                logger.error("dropped oversized line: %s", line)
                out.write(json.dumps({"error": str(line)}) + "\n")
                out.flush()
                continue
            line = line.strip()
            if not line:
                flush()
                continue
            try:
                obj = json.loads(line)
            except ValueError as e:
                logger.error("bad request line: %s", e)
                out.write(json.dumps({"error": str(e)}) + "\n")
                continue
            cmd = obj.get("cmd") if isinstance(obj, dict) else None
            if cmd == "swap":
                flush()  # everything buffered scores on the pre-swap version
                target = cmd_target(obj)
                if target is None:
                    continue
                tsw, _tstore = target
                ok = tsw.swap(obj["model_dir"])
                out.write(json.dumps({
                    "swap": "ok" if ok else "rejected",
                    "generation": tsw.engine.store.generation,
                    "version": tsw.engine.store.version,
                    "delta_version": tsw.delta_version}) + "\n")
                out.flush()
            elif cmd == "delta":
                flush()  # pending requests score pre-delta coefficients
                target = cmd_target(obj)
                if target is None:
                    continue
                tsw, _tstore = target
                ok = tsw.apply_delta(obj.get("coordinate"),
                                     obj.get("entity"),
                                     obj.get("row") or ())
                out.write(json.dumps({
                    "delta": "ok" if ok else "rejected",
                    "delta_version": tsw.delta_version}) + "\n")
                out.flush()
            elif cmd == "rebalance":
                if fleet is not None and obj.get("model") is None:
                    moves = fleet.rebalance()
                    out.write(json.dumps({"rebalance": {
                        mid: {cid: list(m) for cid, m in mm.items()}
                        for mid, mm in moves.items()}}) + "\n")
                    out.flush()
                    continue
                target = cmd_target(obj)
                if target is None:
                    continue
                _tsw, tstore = target
                moves = tstore.rebalance()
                out.write(json.dumps({"rebalance": {
                    cid: list(m) for cid, m in moves.items()}}) + "\n")
                out.flush()
            elif cmd == "fleet":
                flush()
                if router is None:
                    out.write(json.dumps({"error": "no fleet configured; "
                                          "run with --add-model"}) + "\n")
                else:
                    out.write(json.dumps({"fleet": router.status()}) + "\n")
                out.flush()
            elif cmd == "canary":
                flush()  # the episode starts with zero requests in flight
                if router is None:
                    out.write(json.dumps({"error": "no fleet configured; "
                                          "run with --add-model"}) + "\n")
                else:
                    try:
                        handle = fleet.resolve(obj.get("model"))
                        policy = _canary_policy_from(obj, canary_defaults)
                        candidate = _load_fleet_store(
                            engine, obj["model_dir"], handle.store.config)
                        ctl = router.start_canary(
                            handle.model_id, candidate, policy=policy,
                            model_dir=obj["model_dir"])
                        out.write(json.dumps({"canary": ctl.status()})
                                  + "\n")
                    except (KeyError, ValueError, ModelLoadError) as e:
                        out.write(json.dumps({"error": str(e)}) + "\n")
                out.flush()
            elif cmd in ("promote", "rollback"):
                flush()  # settle with zero requests in flight (quiesce)
                if router is None:
                    out.write(json.dumps({"error": "no fleet configured; "
                                          "run with --add-model"}) + "\n")
                else:
                    try:
                        handle = fleet.resolve(obj.get("model"))
                        if cmd == "promote":
                            ctl = router.promote(handle.model_id)
                        else:
                            ctl = router.rollback(
                                handle.model_id,
                                reason=obj.get("reason", "operator"))
                        out.write(json.dumps({cmd: ctl.status()}) + "\n")
                    except ValueError as e:
                        out.write(json.dumps({"error": str(e)}) + "\n")
                out.flush()
            elif cmd == "shadow":
                flush()
                if router is None:
                    out.write(json.dumps({"error": "no fleet configured; "
                                          "run with --add-model"}) + "\n")
                else:
                    try:
                        handle = fleet.resolve(obj.get("model"))
                        if obj.get("off"):
                            ok = router.detach_shadow(handle.model_id)
                            out.write(json.dumps(
                                {"shadow": "off" if ok else "none",
                                 "model": handle.model_id}) + "\n")
                        else:
                            store = _load_fleet_store(
                                engine, obj["model_dir"],
                                handle.store.config)
                            router.attach_shadow(handle.model_id, store)
                            out.write(json.dumps(
                                {"shadow": "on", "model": handle.model_id,
                                 "version": store.version}) + "\n")
                    except (KeyError, ValueError, ModelLoadError) as e:
                        out.write(json.dumps({"error": str(e)}) + "\n")
                out.flush()
            elif cmd == "metrics":
                flush()
                if obj.get("format") == "prometheus":
                    out.write(json.dumps(
                        {"prometheus": engine.metrics.to_prometheus()}) + "\n")
                else:
                    out.write(engine.metrics.to_json() + "\n")
                out.flush()
            elif cmd == "trace":
                flush()  # pending spans (flush/execute) land in the ring
                from photon_ml_tpu import obs

                tracer = obs.get_tracer()
                if not tracer.enabled:
                    out.write(json.dumps(
                        {"error": "tracing disabled; rerun with --trace"})
                        + "\n")
                else:
                    out.write(json.dumps(tracer.chrome_trace()) + "\n")
                out.flush()
            elif cmd == "flight":
                from photon_ml_tpu.obs.pulse import get_flight

                recorder = get_flight()
                if recorder is None:
                    out.write(json.dumps(
                        {"error": "flight recorder not configured; rerun "
                                  "with --flight-dir"}) + "\n")
                else:
                    out.write(json.dumps(
                        {"flight": recorder.snapshot()}) + "\n")
                out.flush()
            elif cmd == "watch":
                flush()  # pending work lands in the counters first
                if not watch_exporter:
                    from photon_ml_tpu.obs.trace import get_process_label
                    from photon_ml_tpu.obs.watch import DeltaExporter

                    watch_exporter.append(DeltaExporter(
                        engine.metrics.registry,
                        label=get_process_label() or "serve"))
                out.write(json.dumps(
                    {"watch": watch_exporter[0].frame()}) + "\n")
                out.flush()
            elif cmd is not None:
                out.write(json.dumps({"error": f"unknown cmd {cmd!r}"}) + "\n")
            else:
                try:
                    req = request_from_json(obj)
                except (ValueError, TypeError) as e:
                    logger.error("bad request: %s", e)
                    out.write(json.dumps({"error": str(e)}) + "\n")
                    continue
                if trace_sample_n > 0 and req.ctx is None:
                    # sampled always-on tracing: deterministic 1-in-N
                    # context minting at the admission edge
                    from photon_ml_tpu.obs.pulse import maybe_mint
                    req.ctx = maybe_mint(trace_sample_n)
                if fleet is not None:
                    try:
                        handle = fleet.resolve(req.model)
                    except ValueError:
                        out.write(json.dumps(
                            {"uid": req.uid, "error": "unknown_model",
                             "model": req.model}) + "\n")
                        out.flush()
                        continue
                    engine.metrics.observe_fleet_request(handle.model_id,
                                                         handle.tenant)
                    pending.append((req.uid,
                                    model_batcher(handle.model_id)
                                    .submit(req)))
                    drain(block=False)
                elif sync:
                    buffered.append(req)
                    if len(buffered) >= engine.batcher.max_batch:
                        flush()
                else:
                    pending.append((req.uid, batcher.submit(req)))
                    drain(block=False)
        flush()
    finally:
        for b in all_batchers():
            b.shutdown(drain=True)
        if not sync:
            drain(block=True)
    return 0


def _parse_listen(listen: str) -> Tuple[str, int]:
    host, sep, port = listen.rpartition(":")
    if not sep:
        raise ValueError(f"wanted host:port, got {listen!r}")
    return host or "127.0.0.1", int(port)


def _auth_token(args: argparse.Namespace) -> Optional[str]:
    """--auth-token, falling back to $PHOTON_AUTH_TOKEN (empty = unset)."""
    if args.auth_token is not None:
        return args.auth_token or None
    return os.environ.get("PHOTON_AUTH_TOKEN") or None


def _parse_add_model(spec: str) -> Tuple[str, str, str]:
    """``NAME=DIR[,tenant=T]`` -> (name, dir, tenant)."""
    name, sep, rest = spec.partition("=")
    if not sep or not name or not rest:
        raise ValueError(
            f"--add-model wants NAME=DIR[,tenant=T], got {spec!r}")
    path, tenant = rest, "default"
    if ",tenant=" in rest:
        path, _, tenant = rest.partition(",tenant=")
    if not path or not tenant:
        raise ValueError(
            f"--add-model wants NAME=DIR[,tenant=T], got {spec!r}")
    return name, path, tenant


def _parse_pairs(specs: Sequence[str], flag: str) -> dict:
    """Repeatable ``KEY=VALUE`` flags -> dict."""
    out = {}
    for spec in specs:
        key, sep, value = spec.partition("=")
        if not sep or not key or not value:
            raise ValueError(f"{flag} wants KEY=VALUE, got {spec!r}")
        out[key] = value
    return out


def _canary_defaults(args: argparse.Namespace) -> dict:
    """CLI-level CanaryPolicy defaults for ``{"cmd": "canary"}`` lines."""
    return {"fraction": args.canary_fraction,
            "min_observations": args.canary_min_observations,
            "max_drift": args.canary_max_drift}


def _load_fleet_store(engine: ScoringEngine, model_dir: str,
                      config: StoreConfig) -> CoefficientStore:
    """Load a canary/shadow leg on the handle's own StoreConfig, so its
    signature — and therefore its warmed executables — is shared with the
    active generation."""
    bundle = load_model_bundle(model_dir)
    return CoefficientStore.from_bundle(bundle, config=config,
                                        version=model_dir,
                                        metrics=engine.metrics)


def _canary_policy_from(obj: dict, defaults: Optional[dict] = None):
    """CanaryPolicy for a ``{"cmd": "canary"}`` line: CLI defaults under
    per-command overrides."""
    from photon_ml_tpu.serving.fleet.policy import CanaryPolicy
    kw = dict(defaults or {})
    for key, cast in (("fraction", float), ("min_observations", int),
                      ("max_drift", float)):
        if obj.get(key) is not None:
            kw[key] = cast(obj[key])
    return CanaryPolicy(**kw)


def _run_network(engine: ScoringEngine, swapper: HotSwapper,
                 args: argparse.Namespace, health=None,
                 watchdog=None, fleet=None) -> int:
    """--listen mode: the serving.frontend edge on an asyncio loop this
    process owns, with an optional same-loop /metrics scrape endpoint and
    SIGTERM/SIGINT wired to the graceful drain."""
    from photon_ml_tpu.serving.frontend.admission import AdmissionConfig
    from photon_ml_tpu.serving.frontend.metrics_http import MetricsEndpoint
    from photon_ml_tpu.serving.frontend.server import (FrontendConfig,
                                                       FrontendServer)

    host, port = _parse_listen(args.listen)
    tenant_tokens = {tok: tenant for tenant, tok in
                     _parse_pairs(args.tenant_token,
                                  "--tenant-token").items()}
    config = FrontendConfig(
        host=host, port=port,
        max_line_bytes=args.max_line_bytes,
        admission=AdmissionConfig(
            budget_s=args.admission_budget_ms * 1e-3,
            resume_fraction=args.resume_fraction,
            client_budget_s=(args.client_budget_ms * 1e-3
                             if args.client_budget_ms else None),
            tenant_budget_s=(args.tenant_budget_ms * 1e-3
                             if args.tenant_budget_ms else None),
            shard_budget_s=(args.shard_budget_ms * 1e-3
                            if args.shard_budget_ms else None),
            fleet_burn_budget=(args.fleet_burn_budget or None)),
        batcher_deadline_s=args.deadline_us * 1e-6,
        dispatch_window=(args.dispatch_window or None),
        predict_mean=args.predict_mean,
        max_connections=(args.max_connections or None),
        auth_token=_auth_token(args),
        tenant_tokens=tenant_tokens or None,
        trace_sample_n=args.trace_sample,
        canary_defaults=_canary_defaults(args))

    async def _main() -> int:
        front = FrontendServer(engine, swapper, config, fleet=fleet,
                               health=health)
        await front.start()
        if watchdog is not None:
            # the edge batcher exists only after start(): watch it too
            front.batcher.watch = watchdog.register(
                "batcher", front.batcher.worker_thread)
        scrape = None
        if args.metrics_port:
            scrape = await MetricsEndpoint(
                engine.metrics, port=args.metrics_port,
                health=health, exemplars=args.exemplars).start()
            logger.info("metrics scrape on http://127.0.0.1:%d/metrics "
                        "(+ /healthz, /readyz)", scrape.port)
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(
                    sig, lambda: asyncio.ensure_future(front.aclose()))
            except (NotImplementedError, RuntimeError, ValueError):
                pass  # non-main thread / platform without signal support
        try:
            await front.wait_closed()
        finally:
            if scrape is not None:
                await scrape.aclose()
        return 0

    return asyncio.run(_main())


def run(argv: List[str]) -> int:
    logging.basicConfig(level=logging.INFO, stream=sys.stderr,
                        format="%(asctime)s %(name)s %(message)s")
    args = build_parser().parse_args(argv)

    from photon_ml_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()

    if args.trace or args.trace_out:
        from photon_ml_tpu import obs

        obs.enable_tracing(capacity=args.trace_buffer)
        logger.info("tracing enabled (ring capacity %d)", args.trace_buffer)

    from photon_ml_tpu.obs import pulse

    pulse.configure(args.trace_label or
                    ("replica" if args.subscribe else "frontend"))
    if args.flight_dir:
        pulse.set_flight(pulse.FlightRecorder(
            args.flight_dir, max_bytes=args.flight_max_bytes))
        logger.info("flight recorder spooling to %s (cap %d bytes)",
                    args.flight_dir, args.flight_max_bytes)
    if args.exemplars:
        from photon_ml_tpu.obs.registry import enable_exemplars

        enable_exemplars(True)

    buckets = None
    if args.buckets:
        buckets = [int(b) for b in args.buckets.split(",") if b.strip()]

    client = None
    metrics = None
    model_dir = args.model_dir
    delta_log = None
    if args.subscribe:
        if args.model_dir or args.delta_log:
            logger.error("--subscribe is mutually exclusive with "
                         "--model-dir / --delta-log (the subscription "
                         "provides both the base and the delta feed)")
            return 1
        if not args.spool:
            logger.error("--subscribe needs --spool DIR (mirror log + "
                         "snapshot bases + resume state live there)")
            return 1
        from photon_ml_tpu.online.delta_log import DeltaLog
        from photon_ml_tpu.online.replication import (
            ReplicationClient, ReplicationClientConfig)

        metrics = ServingMetrics()
        try:
            host, port = _parse_listen(args.subscribe)
        except ValueError as e:
            logger.error("--subscribe: %s", e)
            return 1
        client = ReplicationClient(
            ReplicationClientConfig(host=host, port=port,
                                    spool_dir=args.spool,
                                    auth_token=_auth_token(args)),
            registry=metrics.registry).start()
        logger.info("subscribing to photonrepl owner %s:%d (spool %s)",
                    host, port, args.spool)
        try:
            model_dir = client.bootstrap(timeout=args.bootstrap_timeout)
        except RuntimeError as e:
            logger.error("--subscribe: %s", e)
            client.stop()
            return 1
        logger.info("photonrepl bootstrap: base %s (owner floor gen %s)",
                    model_dir, client.floor)
        # the mirror is OURS but the swapper must treat it as a follower
        # log: identities in it belong to the owner, and the replication
        # client is its only writer/compactor
        delta_log = DeltaLog(client.mirror_path, fsync="never")
    elif not args.model_dir:
        logger.error("--model-dir is required (or --subscribe)")
        return 1
    elif args.delta_log:
        from photon_ml_tpu.online.delta_log import DeltaLog

        # follower role: this process never appends (its process-local
        # generation numbers would corrupt the writer's identity order)
        # and never compacts; fsync is moot for a pure reader
        delta_log = DeltaLog(args.delta_log, fsync="never")
    try:
        engine, swapper = build_server(
            model_dir,
            max_batch=args.max_batch,
            bucket_sizes=buckets,
            device_entity_capacity=(args.device_entity_capacity or None),
            lru_capacity=args.lru_capacity,
            hot_decay=args.hot_decay,
            mesh_shards=args.mesh_shards,
            warm=not args.no_warm,
            metrics=metrics,
            delta_log=delta_log,
            log_owner=False,
            load_aware_routing=not args.no_load_aware_routing,
            replicate_top_k=args.replicate_top_k)
    except (ModelLoadError, ValueError) as e:
        logger.error("--model-dir: %s", e)
        if client is not None:
            client.stop()
        return 1
    logger.info("serving generation %d (version %r), task %s",
                engine.store.generation, engine.store.version,
                engine.store.task.value)

    # photonwatch: every process exports who it is; --watch additionally
    # turns on span-aligned device-time attribution for serve.execute
    from photon_ml_tpu.obs.registry import export_build_info

    export_build_info(engine.metrics.registry,
                      role="replica" if args.subscribe else "frontend")
    if args.watch:
        from photon_ml_tpu.obs.watch import enable_attribution

        enable_attribution(engine.metrics.registry)
        logger.info("photonwatch: device-time attribution enabled")

    if client is not None:
        swapper.set_base(model_dir, client.floor or 0)
        # owner hot swap mid-stream: the client extracts the shipped base
        # and we swap to it; replay_floor is the OWNER's generation for
        # that base, so replay-before-activate off the mirror skips
        # records the snapshot supersedes
        client.on_snapshot = \
            lambda d, g: swapper.swap(d, replay_floor=g)
        if client.model_dir != model_dir:
            # a snapshot landed between bootstrap() and the wiring above —
            # catch up now instead of serving a base the owner replaced
            swapper.swap(client.model_dir, replay_floor=client.floor)

    follower = None
    if delta_log is not None:
        from photon_ml_tpu.online.catchup import LogFollower

        follower = LogFollower(delta_log, lambda: engine.store,
                               poll_interval_s=args.delta_log_poll,
                               registry=engine.metrics.registry)
        stats = follower.run_once()  # initial catch-up BEFORE serving
        logger.info("delta-log catch-up: applied %d, rejected %d "
                    "(position %s); following %s every %.3fs",
                    stats.applied, stats.rejected, stats.position,
                    delta_log.path, args.delta_log_poll)
        follower.start()

    hotset = None
    if args.hot_set_interval > 0:
        hotset = HotSetManager(lambda: engine.store,
                               interval_s=args.hot_set_interval).start()
        logger.info("hot-set rebalancing every %.3fs", args.hot_set_interval)

    # readiness surface (/readyz on --metrics-port): engine warmed AND the
    # delta feed writable/fresh AND no registered worker stalled.  Built
    # unconditionally — cheap, and the bench/tests read it in-process.
    from photon_ml_tpu.chaos.health import (HealthState, Watchdog,
                                            delta_log_check,
                                            follower_staleness_check)

    health = HealthState(registry=engine.metrics.registry)
    watchdog = Watchdog(stall_after_s=args.staleness_bound,
                        registry=engine.metrics.registry)
    health.add_check("workers", watchdog.check)
    health.set_condition(
        "engine_warmed", True,
        "warm skipped (--no-warm)" if args.no_warm
        else "bucket ladder compiled at startup")
    if delta_log is not None:
        health.add_check("delta_log", delta_log_check(delta_log))
    if follower is not None:
        health.add_check("catchup", follower_staleness_check(
            follower, args.staleness_bound))
        follower.watch = watchdog.register("follower",
                                           follower.worker_thread)
    if client is not None:
        watchdog.register("subscriber", client.worker_thread)

    fleet = None
    if args.add_model:
        from photon_ml_tpu.serving.fleet import FleetError, ModelFleet

        try:
            quotas = {t: int(v) for t, v in
                      _parse_pairs(args.tenant_quota,
                                   "--tenant-quota").items()}
            fleet = ModelFleet(metrics=engine.metrics,
                               total_rows=(args.fleet_budget or None),
                               quotas=quotas)
            # the primary engine's warmed kernel cache becomes the fleet
            # cache; every added model's engine is built on it
            fleet.adopt(args.model_name, engine, swapper)
            for spec in args.add_model:
                name, path, tenant = _parse_add_model(spec)
                fleet.register_dir(name, path, tenant=tenant,
                                   config=engine.store.config)
                logger.info("fleet: registered model %r from %s "
                            "(tenant %r)", name, path, tenant)
        except (FleetError, ModelLoadError, ValueError) as e:
            logger.error("--add-model: %s", e)
            if follower is not None:
                follower.stop()
            if client is not None:
                client.stop()
            return 1
        logger.info("fleet: %d model(s), %d shared executable(s), "
                    "%d compile(s)", len(fleet), len(fleet.kernels),
                    fleet.kernels.compile_count)

    slo_thread = None
    if args.slo:
        from photon_ml_tpu.obs.watch import SLOEngine, SLOEvalThread, load_slos

        try:
            slos = load_slos(args.slo)
        except (OSError, ValueError) as e:
            logger.error("--slo: %s", e)
            if follower is not None:
                follower.stop()
            if client is not None:
                client.stop()
            return 1
        slo_thread = SLOEvalThread(SLOEngine(slos),
                                   lambda: engine.metrics.registry,
                                   interval_s=args.slo_interval).start()
        logger.info("photonwatch: evaluating %d SLO(s) every %.3fs",
                    len(slos), args.slo_interval)

    metrics_sidecar = None
    try:
        if args.listen:
            rc = _run_network(engine, swapper, args, health=health,
                              watchdog=watchdog, fleet=fleet)
        else:
            if args.metrics_port:
                from photon_ml_tpu.serving.frontend.metrics_http import \
                    ThreadedMetricsEndpoint

                metrics_sidecar = ThreadedMetricsEndpoint(
                    engine.metrics, port=args.metrics_port,
                    health=health, exemplars=args.exemplars).start()
                logger.info("metrics scrape on http://127.0.0.1:%d/metrics"
                            " (+ /healthz, /readyz)", metrics_sidecar.port)
            lines = sys.stdin if args.requests == "-" \
                else open(args.requests)
            try:
                rc = _serve_stream(engine, swapper, lines, sys.stdout,
                                   args.predict_mean,
                                   deadline_s=args.deadline_us * 1e-6,
                                   sync=args.sync_batcher,
                                   max_line_bytes=args.max_line_bytes,
                                   fleet=fleet, health=health,
                                   canary_defaults=_canary_defaults(args),
                                   trace_sample_n=args.trace_sample)
            finally:
                if lines is not sys.stdin:
                    lines.close()
    finally:
        if slo_thread is not None:
            slo_thread.stop()
        if follower is not None:
            follower.stop()
        if client is not None:
            client.stop()
        if metrics_sidecar is not None:
            metrics_sidecar.stop()
        if hotset is not None:
            hotset.stop()
        if args.metrics_json:
            engine.metrics.export(args.metrics_json)
            logger.info("metrics -> %s", args.metrics_json)
        if args.trace_out:
            from photon_ml_tpu import obs

            obs.get_tracer().export_chrome_trace(args.trace_out)
            logger.info("trace -> %s", args.trace_out)
    return rc


def main() -> None:
    sys.exit(run(sys.argv[1:]))


if __name__ == "__main__":
    main()
