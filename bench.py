"""Benchmark: GLMix 2-coordinate training throughput on the local accelerator.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Config #3 of BASELINE.md (GLMix 2-coordinate: global fixed + per-user random
effect, logistic).  The reference publishes no numbers (BASELINE.json
published: {}), so vs_baseline is measured against a self-contained CPU
numpy/scipy implementation of the same training loop run on this machine —
the stand-in for the reference's Spark-CPU execution model (single-node
local[*] is also how the reference's own regression baselines were captured,
GameTrainingDriverIntegTest.scala:79-80).

Two accelerator implementations of the identical training semantics:
  fused — the whole coordinate-descent sweep as ONE jitted scan program
          (game/fused.FusedSweep), no host round-trips; tried first, in a
          watchdog subprocess so a pathological compile/backend hang falls
          back instead of wedging the bench;
  host  — the host-paced CoordinateDescent loop (one dispatch per phase).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

OUTER = 2


def _synth(rng, n_users=2048, per_user=256, d_global=256, d_user=16, dtype=np.float32):
    """Synthetic GLMix workload at production-representative scale: 524k
    samples, 2048 entities — large enough that the accelerator's objective
    passes are HBM/MXU-bound rather than dispatch-latency-bound (the
    reference's target is LinkedIn-production CTR datasets, README.md:56)."""
    n = n_users * per_user
    xg = rng.normal(size=(n, d_global)).astype(dtype)
    xu = rng.normal(size=(n, d_user)).astype(dtype)
    uids = np.repeat(np.arange(n_users), per_user)
    wg = (rng.normal(size=d_global) * 0.5).astype(dtype)
    wu = (rng.normal(size=(n_users, d_user)) * 1.0).astype(dtype)
    logits = xg @ wg + np.einsum("nd,nd->n", xu, wu[uids])
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(dtype)
    perm = rng.permutation(n)
    return xg[perm], xu[perm], uids[perm], y[perm]


def _build_coordinates(xg, xu, uids, y):
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import FixedEffectConfig, GameData, RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import TaskType

    data = GameData(y=y, features={"g": xg, "u": xu}, id_tags={"userId": uids})
    solver = SolverConfig(max_iters=30, tolerance=1e-7)
    task = TaskType.LOGISTIC_REGRESSION
    # PHOTON_BENCH_STORAGE=bfloat16 flips on mixed-precision design-matrix
    # storage (f32 solver state/accumulation — README "Mixed precision")
    storage = os.environ.get("PHOTON_BENCH_STORAGE") or None
    return {
        "fixed": build_coordinate(
            "fixed", data, FixedEffectConfig(feature_shard="g", solver=solver,
                                             reg=Regularization(l2=1.0),
                                             storage_dtype=storage), task),
        "per-user": build_coordinate(
            "per-user", data,
            RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                               solver=solver, reg=Regularization(l2=1.0),
                               storage_dtype=storage), task),
    }


def bench_accel(xg, xu, uids, y, impl: str):
    """Steady-state training seconds for OUTER full coordinate-descent
    sweeps (device layout + compiles excluded via one warm-up run) — the
    analog of timing the reference's training loop after RDDs materialize."""
    from photon_ml_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache()
    coords = _build_coordinates(xg, xu, uids, y)
    if impl == "fused":
        from photon_ml_tpu.game.fused import FusedSweep

        sweep = FusedSweep(coords, num_iterations=OUTER)
        sweep.run()  # warm-up: compiles the whole-descent program once
        t0 = time.perf_counter()
        sweep.run()
        return time.perf_counter() - t0
    from photon_ml_tpu.game import CoordinateDescent

    descent = CoordinateDescent(coords, num_iterations=OUTER)
    descent.run()  # warm-up: compiles every solver once
    t0 = time.perf_counter()
    descent.run()
    return time.perf_counter() - t0


def bench_cpu_reference(xg, xu, uids, y, l2=1.0):
    """Spark-CPU stand-in: scipy L-BFGS fixed effect + per-user serial scipy
    solves, same residual coordinate-descent loop."""
    import scipy.optimize as sopt
    import scipy.special as sp

    n, dg = xg.shape
    du = xu.shape[1]
    users = np.unique(uids)
    rows_of = {u: np.nonzero(uids == u)[0] for u in users}

    def nll(w, X, yy, off):
        z = X @ w + off
        return np.sum(np.logaddexp(0, z) - yy * z) + 0.5 * l2 * w @ w

    def grad(w, X, yy, off):
        z = X @ w + off
        return X.T @ (sp.expit(z) - yy) + l2 * w

    wg = np.zeros(dg)
    wu = np.zeros((len(users), du))
    fixed_scores = np.zeros(n)
    rand_scores = np.zeros(n)
    t0 = time.perf_counter()
    for _ in range(OUTER):
        off = rand_scores
        r = sopt.minimize(nll, wg, jac=grad, args=(xg, y, off), method="L-BFGS-B",
                          options={"maxiter": 30})
        wg = r.x
        fixed_scores = xg @ wg
        for ui, u in enumerate(users):
            idx = rows_of[u]
            r = sopt.minimize(nll, wu[ui], jac=grad,
                              args=(xu[idx], y[idx], fixed_scores[idx]),
                              method="L-BFGS-B", options={"maxiter": 30})
            wu[ui] = r.x
        rand_scores = np.einsum("nd,nd->n", xu, wu[np.searchsorted(users, uids)])
    return time.perf_counter() - t0


def _impl_subprocess(impl: str, timeout: int):
    """Run one accelerator impl in a watchdog subprocess; returns dt or None.
    EVERY accelerator touch lives in a subprocess: a wedged device backend
    (e.g. the tunnel after an abrupt client kill) then costs one timeout
    instead of hanging the whole bench."""
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--impl", impl],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if out.returncode == 0:
            return json.loads(out.stdout.strip().splitlines()[-1])["dt"]
        sys.stderr.write(f"{impl} bench failed (rc {out.returncode})\n"
                         f"{out.stderr[-2000:]}\n")
    except (subprocess.TimeoutExpired, json.JSONDecodeError, KeyError,
            IndexError, TypeError) as e:
        sys.stderr.write(f"{impl} bench unusable ({e})\n")
    return None


def _accel_seconds(data=None):
    """(dt of the preferred accelerator impl, dataset) — fused first, host
    loop as fallback, both in watchdog subprocesses.  ``data`` lets the
    caller pass pre-synthesized arrays for the inline paths."""
    impl = os.environ.get("PHOTON_BENCH_IMPL")
    if impl in ("fused", "host"):
        data = data if data is not None else _synth(np.random.default_rng(42))
        return bench_accel(*data, impl), data
    fused_to = int(os.environ.get("PHOTON_BENCH_FUSED_TIMEOUT", 2400))
    host_to = int(os.environ.get("PHOTON_BENCH_HOST_TIMEOUT", 1200))
    dt = _impl_subprocess("fused", timeout=fused_to)
    if dt is None:
        sys.stderr.write("falling back to host loop\n")
        dt = _impl_subprocess("host", timeout=host_to)
    if dt is None:
        raise SystemExit("accelerator unavailable: both fused and host bench "
                         "subprocesses failed/timed out")
    return dt, data


def main():
    if len(sys.argv) >= 3 and sys.argv[1] == "--impl":
        dt = bench_accel(*_synth(np.random.default_rng(42)), sys.argv[2])
        print(json.dumps({"dt": dt}))
        return

    dt_accel, data = _accel_seconds()
    if data is None:  # subprocess path: only the CPU reference needs arrays
        data = _synth(np.random.default_rng(42))
    xg, xu, uids, y = data
    n = len(y)
    examples_per_sec = n * OUTER / dt_accel

    dt_cpu = bench_cpu_reference(xg, xu, uids, y)
    speedup = dt_cpu / dt_accel

    print(json.dumps({
        "metric": "glmix_2coord_examples_per_sec_per_chip",
        "value": round(examples_per_sec, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(speedup, 2),
    }))


if __name__ == "__main__":
    main()
