"""Benchmark: GLMix 2-coordinate training throughput on the local accelerator.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Config #3 of BASELINE.md (GLMix 2-coordinate: global fixed + per-user random
effect, logistic).  The reference publishes no numbers (BASELINE.json
published: {}), so vs_baseline is measured against a self-contained CPU
numpy/scipy implementation of the same training loop run on this machine —
the stand-in for the reference's Spark-CPU execution model (single-node
local[*] is also how the reference's own regression baselines were captured,
GameTrainingDriverIntegTest.scala:79-80).
"""

from __future__ import annotations

import json
import time

import numpy as np


def _synth(rng, n_users=512, per_user=256, d_global=128, d_user=16, dtype=np.float32):
    n = n_users * per_user
    xg = rng.normal(size=(n, d_global)).astype(dtype)
    xu = rng.normal(size=(n, d_user)).astype(dtype)
    uids = np.repeat(np.arange(n_users), per_user)
    wg = (rng.normal(size=d_global) * 0.5).astype(dtype)
    wu = (rng.normal(size=(n_users, d_user)) * 1.0).astype(dtype)
    logits = xg @ wg + np.einsum("nd,nd->n", xu, wu[uids])
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logits))).astype(dtype)
    perm = rng.permutation(n)
    return xg[perm], xu[perm], uids[perm], y[perm]


def bench_tpu(xg, xu, uids, y, outer_iters=2):
    """Steady-state training throughput: coordinates (device data layout +
    jitted solvers) are built once; we time full coordinate-descent sweeps —
    the analog of timing the reference's training loop after the RDDs are
    materialized (not the Avro load)."""
    from photon_ml_tpu.core.regularization import Regularization
    from photon_ml_tpu.game import CoordinateDescent, FixedEffectConfig, GameData, RandomEffectConfig
    from photon_ml_tpu.game.coordinate import build_coordinate
    from photon_ml_tpu.opt.types import SolverConfig
    from photon_ml_tpu.types import TaskType

    data = GameData(y=y, features={"g": xg, "u": xu}, id_tags={"userId": uids})
    solver = SolverConfig(max_iters=30, tolerance=1e-7)
    task = TaskType.LOGISTIC_REGRESSION
    coords = {
        "fixed": build_coordinate(
            "fixed", data, FixedEffectConfig(feature_shard="g", solver=solver,
                                             reg=Regularization(l2=1.0)), task),
        "per-user": build_coordinate(
            "per-user", data,
            RandomEffectConfig(random_effect_type="userId", feature_shard="u",
                               solver=solver, reg=Regularization(l2=1.0)), task),
    }
    descent = CoordinateDescent(coords, num_iterations=outer_iters)
    descent.run()  # warm-up: compiles every solver once
    t0 = time.perf_counter()
    model, _, _ = descent.run()
    dt = time.perf_counter() - t0
    return dt, model


def bench_cpu_reference(xg, xu, uids, y, outer_iters=2, l2=1.0):
    """Spark-CPU stand-in: scipy L-BFGS fixed effect + per-user serial scipy
    solves, same residual coordinate-descent loop."""
    import scipy.optimize as sopt
    import scipy.special as sp

    n, dg = xg.shape
    du = xu.shape[1]
    users = np.unique(uids)
    rows_of = {u: np.nonzero(uids == u)[0] for u in users}

    def nll(w, X, yy, off):
        z = X @ w + off
        return np.sum(np.logaddexp(0, z) - yy * z) + 0.5 * l2 * w @ w

    def grad(w, X, yy, off):
        z = X @ w + off
        return X.T @ (sp.expit(z) - yy) + l2 * w

    wg = np.zeros(dg)
    wu = np.zeros((len(users), du))
    fixed_scores = np.zeros(n)
    rand_scores = np.zeros(n)
    t0 = time.perf_counter()
    for _ in range(outer_iters):
        off = rand_scores
        r = sopt.minimize(nll, wg, jac=grad, args=(xg, y, off), method="L-BFGS-B",
                          options={"maxiter": 30})
        wg = r.x
        fixed_scores = xg @ wg
        for ui, u in enumerate(users):
            idx = rows_of[u]
            r = sopt.minimize(nll, wu[ui], jac=grad,
                              args=(xu[idx], y[idx], fixed_scores[idx]),
                              method="L-BFGS-B", options={"maxiter": 30})
            wu[ui] = r.x
        rand_scores = np.einsum("nd,nd->n", xu, wu[np.searchsorted(users, uids)])
    return time.perf_counter() - t0


def main():
    rng = np.random.default_rng(42)
    xg, xu, uids, y = _synth(rng)
    n = len(y)
    outer = 2

    dt_tpu, _ = bench_tpu(xg, xu, uids, y, outer)
    examples_per_sec = n * outer / dt_tpu

    dt_cpu = bench_cpu_reference(xg, xu, uids, y, outer)
    speedup = dt_cpu / dt_tpu

    print(json.dumps({
        "metric": "glmix_2coord_examples_per_sec_per_chip",
        "value": round(examples_per_sec, 1),
        "unit": "examples/sec/chip",
        "vs_baseline": round(speedup, 2),
    }))


if __name__ == "__main__":
    main()
